"""Explicit shard_map stepper for the covariant SWE formulation.

The multi-chip form of the flagship path: one cube face per device on the
``'panel'`` mesh axis, the fused covariant Pallas RHS kernel running
per-device, and the halo exchange hand-scheduled as the reference's four
race-free stages (deck p.9), each ONE bijective ``lax.ppermute`` over ICI
carrying a single ``(3, halo, n)`` payload — the h strip and both
covariant velocity components together.

Covariant components transform between panel bases, so the receiver
rotates the incoming velocity strips through precomputed per-ghost-slot
2x2 entries (``T[i][j] = e_i^local . a_j^nbr`` — the strip form of
``jaxstream.parallel.vector_halo``'s rotation, built from the same grid
bases, hence bitwise-equal ghosts).  Per-device variation (which edge
exchanges in which stage, reversal flags, rotation entries, edge metric
rows) is carried as *data* sharded ``P('panel')``; the SPMD program is
uniform (same technique as :mod:`jaxstream.parallel.shard_halo`).

Panel-seam conservation: each device also reconstructs, from the same
exchanged strips, BOTH panels' edge-normal velocities and applies the
canonical (link, back) symmetrization algebra of
:func:`jaxstream.ops.pallas.swe_cov._symmetrized_strips` — both sides of
an edge evaluate identical expressions on identical operands, so their
edge fluxes agree bitwise and mass is conserved to roundoff across
devices, matching the single-device fused stepper.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.jax_compat import named_scope, shard_map

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    build_schedule,
)
from ..geometry.cubed_sphere import FACE_AXES
from .halo import read_strip, write_strip

__all__ = ["CovShardProgram", "make_cov_shard_exchange",
           "make_cov_shard_exchange_phases",
           "make_cov_shard_exchange_batched",
           "make_sharded_cov_stepper", "make_sharded_cov_deep_stepper",
           "make_sharded_cov_ensemble_stepper", "deep_extend_static"]

_OUT_SIGN = {EDGE_S: -1.0, EDGE_W: -1.0, EDGE_N: 1.0, EDGE_E: 1.0}


class CovShardProgram:
    """Static ppermute schedule + per-device parameter tables.

    All ``(6, ...)`` tables shard ``P('panel')`` so each device reads its
    own face's rows; everything else about the program is uniform.

    Tables (nstages = 4; leading axis = face):
      edge_sel (6, 4) i32      — my edge exchanging in stage s
      rev_sel  (6, 4) f32 0/1  — pair reverses along-edge order
      is_link  (6, 4) f32 0/1  — am I the pair's canonical 'link' side
      s_link / s_back (6, 4)   — OUT_SIGN of the link / back edge
      T_mine   (6, 4, 4, halo, n) — ghost rotation entries (i*2+j),
                 canonical layout, input = received (my-order) raw comps
      T_oadj   (6, 4, 4, n)    — the OTHER face's adjacent-slot entries
      met_mine / met_oth (6, 4, 2, n) — (m0, m1) edge-face inverse-metric
                 rows of my / the other edge, from the grid's stored
                 metric (oracle-bitwise normals)
    """

    def __init__(self, grid, axis_name: str = "panel"):
        n, halo, m = grid.n, grid.halo, grid.m
        i0, i1 = halo, halo + n
        adj = build_connectivity()
        schedule = build_schedule(adj)
        self.axis_name = axis_name
        self.n, self.halo = n, halo

        self.perms = []
        stage_of = {}
        for s, stage in enumerate(schedule):
            perm = []
            for link, back in stage:
                perm.append((link.face, link.nbr_face))
                perm.append((back.face, back.nbr_face))
                stage_of[(link.face, link.edge)] = (s, link, back, True)
                stage_of[(back.face, back.edge)] = (s, link, back, False)
            self.perms.append(perm)

        # One source of truth for the rotation convention: the fused
        # stepper's canonical tables, sliced per (face, edge).
        from ..ops.pallas.swe_cov import _rotation_tables

        T_all = np.asarray(_rotation_tables(grid))   # (4, 6, 4, halo, n)

        gaa_xf = np.asarray(grid.ginv_aa_xf)
        gab_xf = np.asarray(grid.ginv_ab_xf)
        gab_yf = np.asarray(grid.ginv_ab_yf)
        gbb_yf = np.asarray(grid.ginv_bb_yf)

        def met_of(face, edge):
            if edge in (EDGE_W, EDGE_E):
                fi = i0 if edge == EDGE_W else i1
                return np.stack([gaa_xf[face, i0:i1, fi],
                                 gab_xf[face, i0:i1, fi]])
            fi = i0 if edge == EDGE_S else i1
            return np.stack([gab_yf[face, fi, i0:i1],
                             gbb_yf[face, fi, i0:i1]])

        nst = len(schedule)
        edge_sel = np.zeros((6, nst), np.int32)
        rev_sel = np.zeros((6, nst), np.float32)
        is_link = np.zeros((6, nst), np.float32)
        s_link = np.zeros((6, nst), np.float32)
        s_back = np.zeros((6, nst), np.float32)
        T_mine = np.zeros((6, nst, 4, halo, n), np.float32)
        T_oadj = np.zeros((6, nst, 4, n), np.float32)
        met_mine = np.zeros((6, nst, 2, n), np.float32)
        met_oth = np.zeros((6, nst, 2, n), np.float32)

        for (f, e), (s, link, back, mine_is_link) in stage_of.items():
            other = back if mine_is_link else link
            edge_sel[f, s] = e
            rev_sel[f, s] = float(link.reversed_)
            is_link[f, s] = float(mine_is_link)
            s_link[f, s] = _OUT_SIGN[link.edge]
            s_back[f, s] = _OUT_SIGN[back.edge]
            T_mine[f, s] = T_all[:, f, e]
            T_oadj[f, s] = T_all[:, other.face, other.edge][:, 0, :]
            met_mine[f, s] = met_of(f, e)
            met_oth[f, s] = met_of(other.face, other.edge)

        self.tables = {
            "edge_sel": jnp.asarray(edge_sel),
            "rev_sel": jnp.asarray(rev_sel),
            "is_link": jnp.asarray(is_link),
            "s_link": jnp.asarray(s_link),
            "s_back": jnp.asarray(s_back),
            "T_mine": jnp.asarray(T_mine),
            "T_oadj": jnp.asarray(T_oadj),
            "met_mine": jnp.asarray(met_mine),
            "met_oth": jnp.asarray(met_oth),
        }


def _maybe_flip(row, rev):
    return jnp.where(rev > 0.5, jnp.flip(row, axis=-1), row)


#: Table-row order expected by :func:`apply_cov_cube_recv`.
CUBE_ROW_NAMES = ("edge_sel", "rev_sel", "is_link", "s_link", "s_back",
                  "T_mine", "T_oadj", "met_mine", "met_oth")


def apply_cov_cube_recv(h_blk, u_blk, u_send, recv, rows, write_idx):
    """Shared cube-edge receive: rotate, write ghosts, symmetrize.

    The bitwise-critical half of a cube-edge exchange stage, common to
    the one-face-per-device and block-mesh paths (one source of truth
    for the seam-conservation algebra).  ``rows`` are this device's
    table values in :data:`CUBE_ROW_NAMES` order; ``write_idx`` selects
    the ghost edge to write (4 = inactive no-op, used by boundary
    gating on the block mesh).  Returns ``(h_blk, u_blk, mine)`` with
    ``mine`` the symmetrized edge-normal strip of this stage's edge —
    both sides of the physical edge compute it bitwise-equal.
    """
    e_s, rev, isl, sl, sb, Tm, To, mm, mo = rows
    del e_s

    gu0 = Tm[0] * recv[1] + Tm[1] * recv[2]
    gu1 = Tm[2] * recv[1] + Tm[3] * recv[2]
    writers = [functools.partial(write_strip, face=0, edge=e)
               for e in range(4)] + [lambda b, strip: b]
    ghost = jnp.stack([recv[0], gu0, gu1])           # (3, halo, n)
    blk3 = jnp.concatenate([h_blk[None], u_blk], axis=0)
    blk3 = lax.switch(
        write_idx, [lambda b, st, w=w: w(b, strip=st) for w in writers],
        blk3, ghost,
    )
    h_blk = blk3[0]
    u_blk = blk3[1:3]

    # --- symmetrized edge normal (bitwise on both sides) ----------------
    int_adj = u_send[:, 0, :]                # my adjacent row, my order
    ghost_adj = jnp.stack([gu0[0], gu1[0]])
    ubar = 0.5 * (int_adj + ghost_adj)
    n_mine = mm[0] * ubar[0] + mm[1] * ubar[1]

    # The other panel's own normal, in ITS canonical order.
    oth_int = _maybe_flip(recv[1:3, 0, :], rev)      # back to its order
    my_adj_f = _maybe_flip(int_adj, rev)             # as it received
    oth_ghost = jnp.stack([
        To[0] * my_adj_f[0] + To[1] * my_adj_f[1],
        To[2] * my_adj_f[0] + To[3] * my_adj_f[1],
    ])
    obar = 0.5 * (oth_int + oth_ghost)
    n_oth = mo[0] * obar[0] + mo[1] * obar[1]

    n_link = jnp.where(isl > 0.5, n_mine, n_oth)
    n_back_lo = jnp.where(isl > 0.5, _maybe_flip(n_oth, rev),
                          _maybe_flip(n_mine, rev))
    avg = 0.5 * (sl * n_link - sb * n_back_lo)
    mine = jnp.where(isl > 0.5, sl * avg,
                     _maybe_flip(sb * (-avg), rev))
    return h_blk, u_blk, mine


def ssprk3_sharded_body(f, state, dt):
    """The explicit paths' shared SSPRK3 stage combination."""
    from ..ops.pallas.swe_step import SSPRK3_COEFFS

    (_, _), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    h0, u0 = state["h"], state["u"]
    dh, du = f(h0, u0)
    h1 = h0 + dt * dh
    u1 = u0 + dt * du
    dh, du = f(h1, u1)
    h2 = a2 * h0 + b2 * (h1 + dt * dh)
    u2 = a2 * u0 + b2 * (u1 + dt * du)
    dh, du = f(h2, u2)
    return {"h": a3 * h0 + b3 * (h2 + dt * dh),
            "u": a3 * u0 + b3 * (u2 + dt * du)}


def make_cov_shard_exchange_phases(program: CovShardProgram):
    """``(start, finish)`` — the cube-edge exchange split at the wire.

    ``start(h_blk, u_blk, t)`` reads the canonical boundary strips ONCE
    (the stages write only the ghost ring, so every payload is a
    function of the pre-exchange state) and issues all four stage
    ``ppermute``s immediately; ``finish(h_blk, u_blk, recvs)`` rotates
    the received strips into ghosts and runs the seam symmetrization.
    Nothing between ``start`` and ``finish`` depends on the collectives,
    so the overlapped stepper runs the interior RHS kernel there and
    XLA's async collectives fly under it.  The serialized
    :func:`make_cov_shard_exchange` is ``finish(.., start(..))`` —
    one exchange implementation, two schedules.
    """
    n, halo = program.n, program.halo
    axis = program.axis_name

    def start(h_blk, u_blk, t):
        # Canonical strips for every edge, read once: the stages write
        # only the ghost ring, so the interior strips are loop-invariant.
        with named_scope("exchange_start"):
            hs = jnp.stack([read_strip(h_blk, 0, e, halo, n)
                            for e in range(4)])              # (4, halo, n)
            us = jnp.stack([read_strip(u_blk, 0, e, halo, n)
                            for e in range(4)], axis=1)      # (2, 4, halo, n)
            recvs = []
            for s, perm in enumerate(program.perms):
                rows = tuple(t[name][0, s] for name in CUBE_ROW_NAMES)
                e_s, rev = rows[0], rows[1]
                h_send = jnp.take(hs, e_s, axis=0)
                u_send = jnp.take(us, e_s, axis=1)
                payload = jnp.concatenate([h_send[None], u_send])
                payload = _maybe_flip(payload, rev)        # (3, halo, n)
                recvs.append(
                    (lax.ppermute(payload, axis, perm), u_send, rows))
            return recvs

    def finish(h_blk, u_blk, recvs):
        with named_scope("exchange_finish"):
            sym = jnp.zeros((4, n), jnp.float32)
            for recv, u_send, rows in recvs:
                e_s = rows[0]
                h_blk, u_blk, mine = apply_cov_cube_recv(
                    h_blk, u_blk, u_send, recv, rows, e_s)
                sym = jnp.where(
                    (jnp.arange(4) == e_s)[:, None], mine[None], sym)

            sym_sn = jnp.stack([sym[EDGE_S], sym[EDGE_N]])[None]  # (1, 2, n)
            sym_we = jnp.stack([sym[EDGE_W], sym[EDGE_E]], axis=-1)[None]
            return h_blk, u_blk, sym_sn, sym_we

    return start, finish


def make_cov_shard_exchange(program: CovShardProgram):
    """``exchange(h_blk, u_blk, t) -> (h_blk, u_blk, sym_sn, sym_we)``.

    Local function for use inside ``shard_map`` (one face per device).
    ``h_blk``: (1, M, M); ``u_blk``: (2, 1, M, M) covariant components in
    this panel's basis; ``t`` the device's table rows (leading axis 1).
    Fills cube-edge ghosts in 4 ppermute stages and returns the
    symmetrized edge-normal strips ``sym_sn (1, 2, n) / sym_we (1, n, 2)``
    for the RHS kernel.
    """
    start, finish = make_cov_shard_exchange_phases(program)

    def exchange(h_blk, u_blk, t):
        return finish(h_blk, u_blk, start(h_blk, u_blk, t))

    return exchange


def make_cov_shard_exchange_batched(program: CovShardProgram):
    """Batched ensemble form of :func:`make_cov_shard_exchange`.

    ``exchange(h_blk, u_blk, t) -> (h_blk, u_blk, sym_sn, sym_we)`` over
    a LOCAL member-batched face block — ``h_blk (B, 1, M, M)``, ``u_blk
    (2, B, 1, M, M)`` — implemented as ``jax.vmap`` of the single-member
    exchange over the member axis.  The payload of each of the 4
    schedule stages batches into ONE ``lax.ppermute`` carrying all
    members' strips stacked as ``(B, 3, halo, n)`` (vmap's collective
    batching rule — verified as exactly 4 ppermute eqns in the jaxpr),
    so the per-stage ICI latency chain is paid once per ensemble step
    instead of once per member: collective launch latency amortizes
    B-fold at unchanged per-member wire bytes.  The receive algebra
    (rotations, ghost writes, seam symmetrization) is mapped per member
    with identical per-element arithmetic, so every member's ghosts and
    sym strips are bitwise-equal to a per-member exchange loop (tested
    in tests/test_ensemble.py).
    """
    return jax.vmap(make_cov_shard_exchange(program),
                    in_axes=(0, 1, None), out_axes=(0, 1, 0, 0))


def deep_extend_static(grid, field_ext, depth: int):
    """Re-extend a static ``(6, M, M)`` field to ghost ``depth``.

    The deep-halo blocked stepper's orography prep: interior values are
    re-embedded at the deeper ring, edge ghosts filled by the plain
    copy exchange at ``depth`` (the same continuation-point assignment
    the state exchange uses), corners by the face-local average.  Pure
    and cheap; run once at stepper-build time.
    """
    from .halo import make_halo_exchanger

    n = grid.n
    if field_ext is None:
        return jnp.zeros((6, n + 2 * depth, n + 2 * depth), jnp.float32)
    b_int = grid.interior(field_ext)
    pad = [(0, 0)] * (b_int.ndim - 2) + [(depth, depth), (depth, depth)]
    return make_halo_exchanger(n, depth)(jnp.pad(b_int, pad))


def make_sharded_cov_deep_stepper(model, setup, dt: float,
                                  temporal_block: int, overlap=None,
                                  donate: bool = False):
    """Temporal halo blocking on the one-face-per-device tier.

    ``block(state, t) -> state`` advancing ``temporal_block = k`` SSPRK3
    steps per call with ONE deep halo exchange per block: the 4
    race-free ppermute stages ship ``(3, 3*k*halo, n)`` strips (same
    wire bytes per simulated step as the serialized path — 3k h-deep
    exchanges collapse into one 3kh-deep exchange — but the per-stage
    ICI latency chain is paid once per k steps instead of 12 times per
    step), and the 3k RK stages then run exchange-free on shrinking
    windows: stage i computes a ``(n + 2*(D - (i+1)h))^2`` window from
    the ``(n + 2*(D - i*h))^2`` one, ``D = 3*k*halo`` — redundant
    ghost-band compute instead of collectives (Putman & Lin 2007's
    ghost-consumption argument applied across stages).

    Composes with ``parallelization.overlap_exchange``: the block's one
    deep exchange is issued through the start/finish phase split, and
    with the flag on, stage 0's ghost-free ``(n-2h)^2`` interior core
    is computed between the phases (it reads no exchanged value), so
    the 4-ppermute chain flies under it; the rest of stage 0 is then
    four rectangular ring windows stitched around the core — the PR-1
    interior/band tiling generalized to the deep window (ulp-level vs
    the single-window evaluation, the established split budget).

    Approximation contract (why this tier is opt-in while the fused
    k-step tiers are exact): panel-seam ghosts are face-local
    *continuations* — the deep copy assigns neighbor values to
    continuation points whose mismatch grows with depth, the band then
    evolves under THIS panel's metric, and the bitwise seam
    symmetrization is dropped (each side would compute it from its own
    drifting band copy anyway).  All three effects are the same O(d^2)
    class as the k=1 path's own ghost resampling, so the blocked
    trajectory is consistent to truncation — but NOT to the 1e-6
    ulp-budget the exact tiers hold, and cross-seam mass conservation
    degrades from roundoff to truncation level.  Corner patches (three
    panels meet; no unique continuation exists) use the face-local
    edge-ghost average of :func:`jaxstream.parallel.halo._fill_corners`
    at depth D.  docs/USAGE.md "Temporal halo blocking" quantifies the
    redundant-compute fraction ``((n + 2*3kh)^2 - n^2) / n^2`` per
    first stage and when k > 1 loses.
    """
    from ..geometry.cubed_sphere import build_grid
    from ..ops.pallas.swe_cov import rhs_core_cov
    from ..ops.pallas.swe_rhs import coord_rows, pick_recon
    from ..ops.pallas.swe_step import SSPRK3_COEFFS
    from .halo import _fill_corners

    grid = model.grid
    n, h = grid.n, grid.halo
    k = int(temporal_block)
    if k < 2:
        raise ValueError(
            f"deep stepper needs temporal_block >= 2, got {k} "
            "(k=1 is make_sharded_cov_stepper's serialized path)")
    S = 3  # SSPRK3 stages per step; each consumes `halo` of validity
    D = S * k * h
    if n < D:
        raise ValueError(
            f"temporal_block={k} needs n >= 3*k*halo = {D} (deep strips "
            f"are read from the interior), got n={n}")
    if float(getattr(model, "nu4", 0.0)) != 0.0:
        raise ValueError(
            "temporal_block > 1 on the face tier supports nu4 = 0 only "
            "(the del^4 refill would need its own deep exchange)")
    if setup.mesh is None or setup.panel != 6 or setup.sy * setup.sx != 1:
        raise ValueError(
            f"deep blocked stepper needs a (panel=6, 1, 1) mesh; got "
            f"panel={setup.panel}, y={setup.sy}, x={setup.sx}")
    mesh = setup.mesh

    # Deep-grid program: CovShardProgram is depth-agnostic — built on a
    # halo=D grid it yields D-deep rotation tables and the same 4-stage
    # schedule, so the exchange phases are reused verbatim.
    gdeep = build_grid(n, halo=D, radius=float(grid.radius),
                       dtype=jnp.float32)
    program = CovShardProgram(gdeep)
    ex_start, ex_finish = make_cov_shard_exchange_phases(program)

    xr, xfr, yc, yfc, _ = coord_rows(n, D)
    b_deep = deep_extend_static(grid, model.b_ext, D)
    frames_z = jnp.asarray(
        np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)

    d = float(grid.dalpha)
    kw = dict(halo=h, d=d, radius=float(grid.radius),
              gravity=model.gravity, omega=model.omega)
    # One reconstruction partial per stage output size (3k shrinking
    # windows); all windows are square so one recon serves both axes.
    recons = [pick_recon(model.scheme, h, n + 2 * (D - (i + 1) * h),
                         model.limiter) for i in range(S * k)]
    if overlap is None:
        overlap = bool(getattr(setup, "overlap_exchange", False))
    if overlap:
        # Stage-0 split extents: interior core (n-2h)^2 plus the ring's
        # S/N rows (depth D, full width) and W/E columns.
        no = n + 2 * (D - h)
        recon_core = pick_recon(model.scheme, h, n - 2 * h, model.limiter)
        recon_D = pick_recon(model.scheme, h, D, model.limiter)
        recon_no = pick_recon(model.scheme, h, no, model.limiter)
    (_, _), (a2, b2), (a3, b3) = SSPRK3_COEFFS

    axes = mesh.axis_names
    pstate = {"h": P(axes[0]), "u": P(None, axes[0])}
    ptab = {kk: P(axes[0]) for kk in program.tables}

    def crop(x, c):
        return x[..., c:x.shape[-2] - c, c:x.shape[-1] - c]

    def body(state, tabs, fz, b_loc):
        def embed(x):
            pad = [(0, 0)] * (x.ndim - 2) + [(D, D), (D, D)]
            return jnp.pad(x, pad)

        h_e = embed(state["h"])              # (1, n+2D, n+2D)
        u_e = embed(state["u"])
        fz3 = (fz[0, 0, 0], fz[0, 0, 1], fz[0, 0, 2])
        b_l = b_loc[0]
        core = None
        if overlap:
            # Wire first: stage 0's ghost-free (n-2h)^2 core reads only
            # interior data, so it runs under the in-flight deep
            # exchange (the PR-1 overlap schedule, once per block).
            recvs = ex_start(h_e, u_e, tabs)
            sl_i = slice(D, D + n)
            core = rhs_core_cov(
                fz3, xr[:, sl_i], xfr[:, sl_i], yc[sl_i], yfc[sl_i],
                state["h"][0], state["u"][0, 0], state["u"][1, 0],
                b_l[sl_i, sl_i], None, None,
                n=(n - 2 * h, n - 2 * h), recon=recon_core, **kw)
            h_e, u_e, _, _ = ex_finish(h_e, u_e, recvs)
        else:
            h_e, u_e, _, _ = ex_finish(h_e, u_e,
                                       ex_start(h_e, u_e, tabs))
        h_e = _fill_corners(h_e, D, n)
        u_e = _fill_corners(u_e, D, n)

        hc, uac, ubc = h_e[0], u_e[0, 0], u_e[1, 0]
        stage = 0

        def rhs_win(hf, ua, ub, i):
            # Validity entering stage i is D - i*h: the operand window
            # is the whole current array; coordinates/orography slice to
            # the matching deep-extended offsets.
            off = i * h
            m_in = n + 2 * (D - i * h)
            nv = m_in - 2 * h
            sl = slice(off, off + m_in)
            return rhs_core_cov(
                fz3, xr[:, sl], xfr[:, sl], yc[sl], yfc[sl],
                hf, ua, ub, b_l[sl, sl], None, None,
                n=(nv, nv), recon=recons[i], **kw)

        def rhs_stage0_ring(hf, ua, ub):
            # Finish stage 0 around the precomputed core: four
            # rectangular windows tile the deep ring exactly (S/N rows
            # own the corners; W/E take the remaining rows), stitched
            # into the full (n + 2*(D-h))^2 stage-0 tendency — the
            # make_cov_rhs_band_local tiling at deep width.
            def win(r0, r1, c0, c1, ry, rx):
                # r0..c1 are OUTPUT ranges in deep coordinates; the
                # operand window extends `h` beyond on every side.
                sr = slice(r0 - h, r1 + h)
                sc = slice(c0 - h, c1 + h)
                return rhs_core_cov(
                    fz3, xr[:, sc], xfr[:, sc], yc[sr], yfc[sr],
                    hf[sr, sc], ua[sr, sc], ub[sr, sc], b_l[sr, sc],
                    None, None, n=(r1 - r0, c1 - c0), recon=(ry, rx),
                    **kw)

            r_lo, r_hi = D + h, D + n - h       # core output rows
            dS = win(h, D + h, h, h + no, recon_D, recon_no)
            dN = win(D + n - h, n + 2 * D - h, h, h + no,
                     recon_D, recon_no)
            dW = win(r_lo, r_hi, h, D + h, recon_core, recon_D)
            dE = win(r_lo, r_hi, D + n - h, n + 2 * D - h,
                     recon_core, recon_D)

            def stitch(i):
                mid = jnp.concatenate([dW[i], core[i], dE[i]], axis=-1)
                return jnp.concatenate([dS[i], mid, dN[i]], axis=-2)

            return stitch(0), stitch(1), stitch(2)

        for j in range(k):
            h0, ua0, ub0 = hc, uac, ubc
            if j == 0 and overlap:
                dh, dua, dub = rhs_stage0_ring(hc, uac, ubc)
            else:
                dh, dua, dub = rhs_win(hc, uac, ubc, stage)
            hc = crop(h0, h) + dt * dh
            uac = crop(ua0, h) + dt * dua
            ubc = crop(ub0, h) + dt * dub
            stage += 1
            dh, dua, dub = rhs_win(hc, uac, ubc, stage)
            hc = a2 * crop(h0, 2 * h) + b2 * (crop(hc, h) + dt * dh)
            uac = a2 * crop(ua0, 2 * h) + b2 * (crop(uac, h) + dt * dua)
            ubc = a2 * crop(ub0, 2 * h) + b2 * (crop(ubc, h) + dt * dub)
            stage += 1
            dh, dua, dub = rhs_win(hc, uac, ubc, stage)
            hc = a3 * crop(h0, 3 * h) + b3 * (crop(hc, h) + dt * dh)
            uac = a3 * crop(ua0, 3 * h) + b3 * (crop(uac, h) + dt * dua)
            ubc = a3 * crop(ub0, 3 * h) + b3 * (crop(ubc, h) + dt * dub)
            stage += 1

        return {"h": hc[None], "u": jnp.stack([uac[None], ubc[None]])}

    shard_body = shard_map(
        body, mesh=mesh,
        in_specs=(pstate, ptab, P(axes[0]), P(axes[0])),
        out_specs=pstate,
        check_vma=False,
    )

    tables = {
        kk: jax.device_put(v, NamedSharding(mesh, P(axes[0])))
        for kk, v in program.tables.items()
    }
    fz_sh = jax.device_put(frames_z, NamedSharding(mesh, P(axes[0])))
    b_sh = jax.device_put(b_deep, NamedSharding(mesh, P(axes[0])))

    jitted = jax.jit(lambda state: shard_body(state, tables, fz_sh, b_sh),
                     donate_argnums=(0,) if donate else ())

    def step(state, t):
        del t
        return jitted(state)

    step.steps_per_call = k
    return step


def _make_cov_face_rhs(model, grid, program: CovShardProgram, overlap,
                       platform):
    """Per-face local RHS closure of the explicit face tier.

    Returns ``f(h_int, u_int, tabs, fz, b_loc) -> (dh, du)`` — embed,
    4-stage ppermute exchange (phase-split under ``overlap``), fused
    covariant Pallas RHS kernel, optional del^4 — the single source of
    the face-tier stage arithmetic, shared by the serialized/overlapped
    stepper and the batched ensemble stepper (which vmaps it over the
    member axis: the ppermutes batch into single all-member collectives
    and the per-member math stays op-identical).
    """
    halo, n = grid.halo, grid.n
    exchange = make_cov_shard_exchange(program)
    from ..ops.pallas.swe_cov import make_cov_rhs_pallas

    rhs_local = make_cov_rhs_pallas(
        grid, model.gravity, model.omega, scheme=model.scheme,
        limiter=model.limiter, interpret=(platform != "tpu"),
        n_faces=1, external_sym=True,
    )
    if overlap:
        from ..ops.pallas.swe_cov import (make_cov_rhs_band_local,
                                          make_cov_rhs_interior_local)
        from ..ops.pallas.swe_rhs import coord_rows

        ex_start, ex_finish = make_cov_shard_exchange_phases(program)
        rhs_interior = make_cov_rhs_interior_local(
            n, halo, float(grid.dalpha), float(grid.radius),
            model.gravity, model.omega, scheme=model.scheme,
            limiter=model.limiter, interpret=(platform != "tpu"))
        rhs_band = make_cov_rhs_band_local(
            n, halo, float(grid.dalpha), float(grid.radius),
            model.gravity, model.omega, scheme=model.scheme,
            limiter=model.limiter)
        xr_f, xfr_f, yc_f, yfc_f, _ = coord_rows(n, halo)
        xr_i, xfr_i = xr_f[:, halo:halo + n], xfr_f[:, halo:halo + n]
        yc_i, yfc_i = yc_f[halo:halo + n], yfc_f[halo:halo + n]

    def embed(x):
        pad = [(0, 0)] * (x.ndim - 2) + [(halo, halo), (halo, halo)]
        return jnp.pad(x, pad)

    nu4 = float(getattr(model, "nu4", 0.0))
    if nu4 != 0.0:
        from ..ops.pallas.swe_cov import lap_core
        from ..ops.pallas.swe_rhs import coord_rows
        from .halo import _fill_corners

        x_row, xf_row, x_col, xf_col, _ = coord_rows(grid.n, halo)
        lap1 = functools.partial(
            lap_core, x_row, xf_row, x_col, xf_col,
            n=grid.n, halo=halo, d=float(grid.dalpha),
            radius=float(grid.radius))

    def f(h_int, u_int, tabs, fz, b_loc):
        h_e = embed(h_int)
        u_e = embed(u_int)
        if overlap:
            # Wire first: all 4 stage ppermutes are functions of the
            # pre-exchange strips.  The interior kernel depends on
            # none of them, so the async collectives overlap it; the
            # band pass then consumes the received strips.
            recvs = ex_start(h_e, u_e, tabs)
            with named_scope("rhs_interior"):
                dh_c, du_c = rhs_interior(
                    fz, xr_i, xfr_i, yc_i, yfc_i, h_int, u_int,
                    b_loc[:, halo:halo + n, halo:halo + n])
            h_e, u_e, ssn, swe = ex_finish(h_e, u_e, recvs)
            with named_scope("rhs_band"):
                dh, du = rhs_band(fz, xr_f, xfr_f, yc_f, yfc_f,
                                  h_e, u_e, b_loc, ssn, swe, dh_c, du_c)
        else:
            h_e, u_e, ssn, swe = exchange(h_e, u_e, tabs)
            with named_scope("rhs_face"):
                dh, du = rhs_local(fz, h_e, u_e, b_loc, ssn, swe)
        if nu4 != 0.0:
            # del^4 = lap(lap(.)) with an exchanged refill between,
            # exactly the fused nu4 stepper's structure: the same
            # strip exchange applies (lap of a covariant pair is a
            # covariant pair), and the Laplace-Beltrami cross-terms
            # need the ghost corners (face-local averaging).
            def lap3(he, ue):
                he = _fill_corners(he, halo, grid.n)
                ue = _fill_corners(ue, halo, grid.n)
                return (lap1(he[0])[None],
                        jnp.stack([lap1(ue[0, 0])[None],
                                   lap1(ue[1, 0])[None]]))
            l1h, l1u = lap3(h_e, u_e)
            l1h_e, l1u_e, _, _ = exchange(embed(l1h), embed(l1u), tabs)
            l2h, l2u = lap3(l1h_e, l1u_e)
            dh = dh - nu4 * l2h
            du = du - nu4 * l2u
        return dh, du

    return f


def make_sharded_cov_stepper(model, setup, dt: float, overlap=None,
                             temporal_block: int = 1,
                             donate: bool = False):
    """``step(state, t) -> state`` for the covariant model under shard_map.

    Requires a ``(panel=6, 1, 1)`` mesh (one face per device).  State is
    the usual interior pytree ``{"h": (6, n, n), "u": (2, 6, n, n)}``
    sharded over the panel axis.  Each SSPRK3 stage = one explicit
    4-ppermute exchange + the fused covariant Pallas RHS kernel on the
    local face (interpret mode off-TPU) + the stage combination.

    ``overlap`` (default: the setup's ``overlap_exchange`` flag): issue
    the 4 ppermute stages first, run the interior-only RHS kernel (the
    ghost-free (n-2h)^2 core) while the collectives are in flight, then
    consume the received strips in the boundary-band pass — the
    interior/band split of :mod:`jaxstream.ops.pallas.swe_cov`.  The
    split tiles the exact arithmetic of the fused kernel; compiled
    states agree at the ulp level (XLA re-fuses the differently-shaped
    kernels' surroundings — <= 1e-6 relative over the multi-step parity
    runs in tests/test_overlap_exchange.py); only the collective/compute
    overlap differs.

    ``temporal_block = k > 1`` dispatches to
    :func:`make_sharded_cov_deep_stepper`: k steps per call behind ONE
    3*k*halo-deep exchange (see its docstring for the approximation
    contract; the k=1 path here stays the bitwise reference).  The
    ``overlap`` argument is forwarded — there it schedules stage-0's
    ghost-free core under the deep exchange.
    """
    if temporal_block > 1:
        return make_sharded_cov_deep_stepper(model, setup, dt,
                                             temporal_block,
                                             overlap=overlap,
                                             donate=donate)
    grid = model.grid
    if setup.mesh is None or setup.panel != 6 or setup.sy * setup.sx != 1:
        raise ValueError(
            f"explicit covariant shard path needs a (panel=6, 1, 1) mesh; "
            f"got panel={setup.panel}, y={setup.sy}, x={setup.sx}. Use the "
            f"GSPMD path (use_shard_map: false) for other layouts."
        )
    if overlap is None:
        overlap = getattr(setup, "overlap_exchange", False)
    mesh = setup.mesh
    program = CovShardProgram(grid)
    platform = getattr(mesh.devices.flat[0], "platform", "cpu")
    f_loc = _make_cov_face_rhs(model, grid, program, overlap, platform)
    frames_z = jnp.asarray(
        np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)

    axes = mesh.axis_names                      # ('panel', 'y', 'x')
    pstate = {"h": P(axes[0]), "u": P(None, axes[0])}
    ptab = {k: P(axes[0]) for k in program.tables}

    def body(state, tabs, fz, b_loc):
        return ssprk3_sharded_body(
            lambda h, u: f_loc(h, u, tabs, fz, b_loc), state, dt)

    shard_body = shard_map(
        body, mesh=mesh,
        in_specs=(pstate, ptab, P(axes[0]), P(axes[0])),
        out_specs=pstate,
        check_vma=False,
    )

    tables = {
        k: jax.device_put(v, NamedSharding(mesh, P(axes[0])))
        for k, v in program.tables.items()
    }
    fz_sh = jax.device_put(frames_z, NamedSharding(mesh, P(axes[0])))
    b_sh = jax.device_put(model.b_ext, NamedSharding(mesh, P(axes[0])))

    # donate=True aliases the ping-pong state carry (donate_argnums)
    # so XLA stops double-buffering every prognostic; default off
    # because parity/test callers legitimately step one initial state
    # through several steppers (a donated buffer dies on first use).
    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state, t):
        del t
        return shard_body(state, tables, fz_sh, b_sh)

    return step


def make_sharded_cov_ensemble_stepper(model, setup, dt: float,
                                      members: int, overlap=None,
                                      temporal_block: int = 1,
                                      donate: bool = False,
                                      wrap_jit: bool = True):
    """Batched ensemble stepper on the explicit covariant face tier.

    ``step(state, t) -> state`` over the batched interior state
    ``{"h": (B, 6, n, n), "u": (2, B, 6, n, n)}`` (member-before-face
    layout), advancing all ``B = members`` perturbed-IC members one
    SSPRK3 step (or ``temporal_block`` exactly-fused steps) per call.

    Execution: the single-member face-tier stage closure
    (:func:`_make_cov_face_rhs` — the serialized/overlapped stepper's
    own arithmetic) is ``jax.vmap``-ed over the member axis inside the
    ``shard_map`` body.  Collective batching turns each of the 4
    schedule stages' ppermutes into ONE collective carrying all local
    members' strips stacked ``(B_loc, 3, halo, n)`` — per-stage launch
    latency is paid once per ensemble step instead of once per member,
    per-member wire bytes unchanged — and the per-face Pallas RHS
    kernel batches into a single launch with a leading member grid
    axis.  Per-member values are bitwise-equal to the single-member
    stepper run B times (vmap maps, it does not reassociate).

    Meshes: the plain face tier ``(panel=6, 1, 1)`` (members stacked
    locally per device) or :func:`..mesh.setup_ensemble_sharding`'s 2-D
    ``('panel', 'member')`` mesh, where each device carries
    ``members / setup.member`` members and the member axis adds zero
    wire traffic.  ``temporal_block = k > 1`` fuses k steps in one
    SPMD dispatch (exact — the face tier's deep-halo approximation is
    NOT applied here; the batched exchange already amortizes the
    latency the deep form trades accuracy for).

    ``wrap_jit=False`` (round 12) returns the raw (untraced) step so a
    caller can compose it inside its OWN compiled loop — the
    continuous-batching server's panel-sharded masked segment traces
    it under one ``jax.jit`` around ``stepping.integrate_masked``,
    where a nested jit boundary would block carry donation and
    sharding propagation; the serving loop's per-member nonfinite
    stream is then a plain GSPMD reduction over the shard_map outputs.
    The closed-over program tables/orography stay the device-put
    ``P('panel')`` constants either way (``donate`` only applies to
    the wrapped jit).
    """
    grid = model.grid
    if setup.mesh is None or setup.panel != 6 or setup.sy * setup.sx != 1:
        raise ValueError(
            f"ensemble face stepper needs a (panel=6, ...) face mesh "
            f"(optionally x member); got panel={setup.panel}, "
            f"y={setup.sy}, x={setup.sx}")
    if members < 1:
        raise ValueError(f"members must be >= 1, got {members}")
    mshard = getattr(setup, "member", 1)
    if members % mshard:
        raise ValueError(
            f"members={members} not divisible by the mesh's member-"
            f"shard count {mshard}")
    if temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {temporal_block}")
    if overlap is None:
        overlap = getattr(setup, "overlap_exchange", False)
    mesh = setup.mesh
    program = CovShardProgram(grid)
    platform = getattr(mesh.devices.flat[0], "platform", "cpu")
    f_loc = _make_cov_face_rhs(model, grid, program, overlap, platform)
    frames_z = jnp.asarray(
        np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)

    axes = mesh.axis_names
    member_ax = "member" if "member" in axes else None
    pstate = {"h": P(member_ax, "panel"),
              "u": P(None, member_ax, "panel")}
    ptab = {k: P("panel") for k in program.tables}
    maxes = {"h": 0, "u": 1}

    def body(state, tabs, fz, b_loc):
        def one(st):
            for _ in range(temporal_block):
                st = ssprk3_sharded_body(
                    lambda h, u: f_loc(h, u, tabs, fz, b_loc), st, dt)
            return st

        return jax.vmap(one, in_axes=(maxes,), out_axes=maxes)(state)

    shard_body = shard_map(
        body, mesh=mesh,
        in_specs=(pstate, ptab, P("panel"), P("panel")),
        out_specs=pstate,
        check_vma=False,
    )

    tables = {
        k: jax.device_put(v, NamedSharding(mesh, P("panel")))
        for k, v in program.tables.items()
    }
    fz_sh = jax.device_put(frames_z, NamedSharding(mesh, P("panel")))
    b_sh = jax.device_put(model.b_ext, NamedSharding(mesh, P("panel")))

    if wrap_jit:
        jitted = jax.jit(
            lambda state: shard_body(state, tables, fz_sh, b_sh),
            donate_argnums=(0,) if donate else ())

        def step(state, t):
            del t
            return jitted(state)
    else:
        def step(state, t):
            del t
            return shard_body(state, tables, fz_sh, b_sh)

    step.ensemble = int(members)
    if temporal_block > 1:
        step.steps_per_call = temporal_block
    return step
