"""Explicit block-mesh shard_map stepper for the covariant formulation.

Completes the explicit tier's matrix (DESIGN.md "formulation ×
parallelism"): the covariant flagship on a ``(panel, y, x)`` =
``(6, s, s)`` mesh — the reference's planned ``tiles_per_edge`` scaling
(``/root/reference/JAX-DevLab-Examples.py:31-37``, annotated "3 → 54
tiles" on the config screenshot, deck p.8) with the rotation-form
vector exchange instead of the Cartesian componentwise one.

Structure per SSPRK3 stage, per device (one sub-panel block each):

* **Intra-panel ghosts**: 4 neighbor ``ppermute``s over the 'y'/'x'
  axes carrying one ``(3, halo, n_loc)`` payload (h + both covariant
  components — same basis on both sides, no rotation).
* **Cube edges**: the 4 race-free stages as joint ``ppermute``s over
  the full device product axis (only face-boundary blocks participate);
  receivers rotate the velocity strips through per-device slices of the
  face-level rotation tables (the same ``_rotation_tables`` source of
  truth as every other covariant path).
* **Seam normals**: every block edge gets an imposed edge-normal strip.
  Panel seams use the canonical (link, back) symmetrization algebra on
  the exchanged adjacent rows (bitwise-equal on both sides, as in
  :mod:`.shard_cov`); intra-panel seams need no pair algebra at all —
  ``0.5 * (mine + theirs)`` is bitwise-commutative and both sides scale
  by identical stored-metric rows, so the shared value is exact by
  construction.  Cross-device flux telescoping (mass conservation) is
  therefore exact in both directions.

The per-block RHS runs :func:`...swe_cov.make_cov_rhs_pallas_local`
with the block's own coordinate rows as runtime operands (each device
covers a different patch of its face's gnomonic coordinates).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    build_schedule,
)
from ..geometry.cubed_sphere import FACE_AXES, extended_coords
from .halo import read_strip, write_strip
from .shard_cov import (
    CUBE_ROW_NAMES,
    apply_cov_cube_recv,
    ssprk3_sharded_body,
)
from .shard_halo import _block_coords

__all__ = ["CovBlockProgram", "make_cov_block_exchange",
           "make_cov_block_exchange_phases",
           "make_cov_block_exchange_batched",
           "make_sharded_cov_block_stepper"]

_OUT_SIGN = {EDGE_S: -1.0, EDGE_W: -1.0, EDGE_N: 1.0, EDGE_E: 1.0}


class CovBlockProgram:
    """Static schedule + per-device tables for the covariant block mesh.

    All ``(6, s, s, ...)`` tables shard ``P('panel', 'y', 'x')``; the
    SPMD program is uniform and reads its own rows.
    """

    def __init__(self, grid, s: int, axis_names=("panel", "y", "x")):
        n, halo = grid.n, grid.halo
        if n % s:
            raise ValueError(f"n={n} not divisible by blocks-per-edge {s}")
        n_loc = n // s
        if n_loc < halo:
            raise ValueError(f"local block {n_loc} smaller than halo {halo}")
        self.s = s
        self.n_loc = n_loc
        self.halo = halo
        self.axis_names = tuple(axis_names)
        ax_panel, ax_y, ax_x = self.axis_names
        adj = build_connectivity()
        schedule = build_schedule(adj)
        nst = len(schedule)
        i0f, i1f = halo, halo + n          # face-extended interior range

        # ---- intra-panel neighbor shifts --------------------------------
        fwd = [(i, i + 1) for i in range(s - 1)]
        bwd = [(i + 1, i) for i in range(s - 1)]
        self.intra_perms = [
            (ax_x, fwd, EDGE_E, EDGE_W),
            (ax_x, bwd, EDGE_W, EDGE_E),
            (ax_y, fwd, EDGE_N, EDGE_S),
            (ax_y, bwd, EDGE_S, EDGE_N),
        ]

        # ---- cube-edge stages (joint permutes over boundary blocks) -----
        def lin(f, iy, ix):
            return (f * s + iy) * s + ix

        stage_of = {}
        self.cube_perms = []
        for t, stage in enumerate(schedule):
            perm = []
            for link, back in stage:
                for lk, other, isl in ((link, back, True),
                                       (back, link, False)):
                    for k in range(s):
                        kk = s - 1 - k if lk.reversed_ else k
                        src = lin(lk.face, *_block_coords(lk.edge, k, s))
                        dst = lin(lk.nbr_face,
                                  *_block_coords(lk.nbr_edge, kk, s))
                        perm.append((src, dst))
                        iy, ix = _block_coords(lk.edge, k, s)
                        stage_of[(lk.face, iy, ix, lk.edge)] = (
                            t, link, back, isl, k, kk)
            assert len(set(d for _, d in perm)) == len(perm)
            self.cube_perms.append(perm)

        # ---- per-device tables ------------------------------------------
        from ..ops.pallas.swe_cov import _rotation_tables

        T_all = np.asarray(_rotation_tables(grid))   # (4, 6, 4, halo, n)
        gaa_xf = np.asarray(grid.ginv_aa_xf)
        gab_xf = np.asarray(grid.ginv_ab_xf)
        gab_yf = np.asarray(grid.ginv_ab_yf)
        gbb_yf = np.asarray(grid.ginv_bb_yf)

        def met_seg(face, edge, iy, ix):
            """(2, n_loc) metric rows of block (iy, ix)'s ``edge``."""
            if edge in (EDGE_W, EDGE_E):
                fi = i0f + (ix if edge == EDGE_W else ix + 1) * n_loc
                r0, r1 = i0f + iy * n_loc, i0f + (iy + 1) * n_loc
                return np.stack([gaa_xf[face, r0:r1, fi],
                                 gab_xf[face, r0:r1, fi]])
            fi = i0f + (iy if edge == EDGE_S else iy + 1) * n_loc
            c0, c1 = i0f + ix * n_loc, i0f + (ix + 1) * n_loc
            return np.stack([gab_yf[face, fi, c0:c1],
                             gbb_yf[face, fi, c0:c1]])

        edge_sel = np.zeros((6, s, s, nst), np.int32)
        active = np.zeros((6, s, s, nst), np.float32)
        rev_sel = np.zeros((6, s, s, nst), np.float32)
        is_link = np.zeros((6, s, s, nst), np.float32)
        s_link = np.zeros((6, s, s, nst), np.float32)
        s_back = np.zeros((6, s, s, nst), np.float32)
        T_mine = np.zeros((6, s, s, nst, 4, halo, n_loc), np.float32)
        T_oadj = np.zeros((6, s, s, nst, 4, n_loc), np.float32)
        met_mine = np.zeros((6, s, s, nst, 2, n_loc), np.float32)
        met_oth = np.zeros((6, s, s, nst, 2, n_loc), np.float32)
        met_edge = np.zeros((6, s, s, 4, 2, n_loc), np.float32)

        for f in range(6):
            for iy in range(s):
                for ix in range(s):
                    for e in range(4):
                        met_edge[f, iy, ix, e] = met_seg(f, e, iy, ix)

        for (f, iy, ix, e), (t, link, back, isl, k, kk) in stage_of.items():
            other = back if isl else link
            seg = slice(k * n_loc, (k + 1) * n_loc)
            oseg = slice(kk * n_loc, (kk + 1) * n_loc)
            edge_sel[f, iy, ix, t] = e
            active[f, iy, ix, t] = 1.0
            rev_sel[f, iy, ix, t] = float(link.reversed_)
            is_link[f, iy, ix, t] = float(isl)
            s_link[f, iy, ix, t] = _OUT_SIGN[link.edge]
            s_back[f, iy, ix, t] = _OUT_SIGN[back.edge]
            T_mine[f, iy, ix, t] = T_all[:, f, e][:, :, seg]
            T_oadj[f, iy, ix, t] = T_all[:, other.face, other.edge][
                :, 0, oseg]
            met_mine[f, iy, ix, t] = met_edge[f, iy, ix, e]
            oy, ox = _block_coords(other.edge, kk, s)
            met_oth[f, iy, ix, t] = met_seg(other.face, other.edge, oy, ox)

        # ---- corner-ghost routing (nu4 / Laplacian support) -------------
        # The Laplace-Beltrami cross-terms read the h x h ghost corners.
        # On the block mesh every corner ghost is the END PATCH of some
        # neighbor's already-filled edge-ghost strip: the x-neighbor's
        # S/N strip end for interior columns, the y-neighbor's W/E strip
        # end on the panel-edge columns (where the x-neighbor is across
        # a cube edge and the strip itself already carries the rotated
        # data), and the face-local average at true cube corners —
        # exactly the whole-face oracle's structure.  One-hot source
        # masks per corner in [SW, SE, NW, NE] order:
        use_x = np.zeros((6, s, s, 4), np.float32)
        use_y = np.zeros((6, s, s, 4), np.float32)
        use_avg = np.zeros((6, s, s, 4), np.float32)
        for iy in range(s):
            for ix in range(s):
                for c, (xdir, ydir) in enumerate(
                        [(-1, -1), (+1, -1), (-1, +1), (+1, +1)]):
                    has_x = (ix > 0) if xdir < 0 else (ix < s - 1)
                    has_y = (iy > 0) if ydir < 0 else (iy < s - 1)
                    if has_x:
                        use_x[:, iy, ix, c] = 1.0
                    elif has_y:
                        use_y[:, iy, ix, c] = 1.0
                    else:
                        use_avg[:, iy, ix, c] = 1.0

        # ---- per-device coordinates and frames --------------------------
        ac, af, _ = extended_coords(n, halo)
        xr = np.zeros((6, s, s, 1, n_loc + 2 * halo), np.float32)
        xfr = np.zeros_like(xr)
        yc = np.zeros((6, s, s, n_loc + 2 * halo, 1), np.float32)
        yfc = np.zeros_like(yc)
        for iy in range(s):
            for ix in range(s):
                cseg = slice(ix * n_loc, ix * n_loc + n_loc + 2 * halo)
                rseg = slice(iy * n_loc, iy * n_loc + n_loc + 2 * halo)
                xr[:, iy, ix, 0, :] = np.tan(ac[cseg])
                xfr[:, iy, ix, 0, :] = np.tan(af[cseg])
                yc[:, iy, ix, :, 0] = np.tan(ac[rseg])
                yfc[:, iy, ix, :, 0] = np.tan(af[rseg])
        fz = np.broadcast_to(
            np.asarray(FACE_AXES, np.float32)[:, None, None, None, :, 2],
            (6, s, s, 1, 3)).copy()

        self.tables = {
            "edge_sel": jnp.asarray(edge_sel),
            "active": jnp.asarray(active),
            "rev_sel": jnp.asarray(rev_sel),
            "is_link": jnp.asarray(is_link),
            "s_link": jnp.asarray(s_link),
            "s_back": jnp.asarray(s_back),
            "T_mine": jnp.asarray(T_mine),
            "T_oadj": jnp.asarray(T_oadj),
            "met_mine": jnp.asarray(met_mine),
            "met_oth": jnp.asarray(met_oth),
            "met_edge": jnp.asarray(met_edge),
            "xr": jnp.asarray(xr),
            "xfr": jnp.asarray(xfr),
            "yc": jnp.asarray(yc),
            "yfc": jnp.asarray(yfc),
            "fz": jnp.asarray(fz),
            "corner_use_x": jnp.asarray(use_x),
            "corner_use_y": jnp.asarray(use_y),
            "corner_use_avg": jnp.asarray(use_avg),
        }


def _flip(row, rev):
    return jnp.where(rev > 0.5, jnp.flip(row, axis=-1), row)


def make_cov_block_exchange_phases(program: CovBlockProgram):
    """``(start, finish)`` — the block exchange split at the wire.

    Every payload (intra-panel neighbor shifts AND cube-edge stages) is
    a function of the block's pre-exchange boundary strips, read once —
    so ``start`` issues all of them immediately and ``finish`` applies
    the ghost writes plus both seam-normal algebras.  The overlapped
    stepper runs the interior-only RHS kernel between the two (see
    :func:`jaxstream.parallel.shard_cov.make_cov_shard_exchange_phases`
    for the face-tier twin).
    """
    n, halo = program.n_loc, program.halo
    joint = program.axis_names

    def start(h_blk, u_blk, t):
        def tt(name):
            v = t[name]
            return v.reshape(v.shape[3:])      # drop (1, 1, 1) device dims

        hs = jnp.stack([read_strip(h_blk, 0, e, halo, n)
                        for e in range(4)])                  # (4, halo, n)
        us = jnp.stack([read_strip(u_blk, 0, e, halo, n)
                        for e in range(4)], axis=1)          # (2, 4, halo, n)

        intra = []
        for axname, perm, e_send, e_recv in program.intra_perms:
            if not perm:
                continue
            payload = jnp.concatenate(
                [hs[e_send][None], us[:, e_send]])           # (3, halo, n)
            intra.append((e_recv, lax.ppermute(payload, axname, perm)))

        cube = []
        for st, perm in enumerate(program.cube_perms):
            rows = tuple(tt(name)[st] for name in CUBE_ROW_NAMES)
            e_s, rev = rows[0], rows[1]
            act = tt("active")[st]
            u_send = jnp.take(us, e_s, axis=1)
            payload = _flip(jnp.concatenate(
                [jnp.take(hs, e_s, axis=0)[None], u_send]), rev)
            cube.append((lax.ppermute(payload, joint, perm),
                         u_send, rows, act))
        return us, intra, cube

    def finish(h_blk, u_blk, t, phase):
        def tt(name):
            v = t[name]
            return v.reshape(v.shape[3:])

        us, intra, cube = phase
        sym = jnp.zeros((4, n), jnp.float32)
        met_edge = tt("met_edge")                            # (4, 2, n)

        # ---- intra-panel neighbors (same basis; no rotation) ------------
        writers = [lambda b, st, e=e: write_strip(b, 0, e, st)
                   for e in range(4)] + [lambda b, st: b]
        for e_recv, recv in intra:
            blk3 = jnp.concatenate([h_blk[None], u_blk], axis=0)
            blk3 = writers[e_recv](blk3, recv)
            h_blk = blk3[0]
            u_blk = blk3[1:3]
            # Shared seam normal: 0.5*(mine + theirs) is commutative, so
            # both sides compute the identical value with identical
            # metric rows — no pair algebra needed off the cube edges.
            ubar = 0.5 * (us[:, e_recv, 0, :] + recv[1:3, 0, :])
            n_seam = (met_edge[e_recv, 0] * ubar[0]
                      + met_edge[e_recv, 1] * ubar[1])
            sym = jnp.where((jnp.arange(4) == e_recv)[:, None],
                            n_seam[None], sym)

        # ---- cube-edge stages (shared seam algebra, shard_cov.py) -------
        for recv, u_send, rows, act in cube:
            e_s = rows[0]
            h_blk, u_blk, mine = apply_cov_cube_recv(
                h_blk, u_blk, u_send, recv, rows,
                jnp.where(act > 0.5, e_s, 4))
            sym = jnp.where(
                ((jnp.arange(4) == e_s) & (act > 0.5))[:, None],
                mine[None], sym)

        sym_sn = jnp.stack([sym[EDGE_S], sym[EDGE_N]])[None]     # (1, 2, n)
        sym_we = jnp.stack([sym[EDGE_W], sym[EDGE_E]], axis=-1)[None]
        return h_blk, u_blk, sym_sn, sym_we

    return start, finish


def make_cov_block_exchange(program: CovBlockProgram):
    """``exchange(h_blk, u_blk, t) -> (h_blk, u_blk, sym_sn, sym_we)``.

    Local function for ``shard_map`` over the ``(6, s, s)`` mesh; the
    blocks are local ``(1, m_loc, m_loc)`` / ``(2, 1, m_loc, m_loc)``
    and ``t`` holds this device's table rows (leading dims 1).
    """
    start, finish = make_cov_block_exchange_phases(program)

    def exchange(h_blk, u_blk, t):
        return finish(h_blk, u_blk, t, start(h_blk, u_blk, t))

    return exchange


def make_cov_block_exchange_batched(program: CovBlockProgram):
    """Batched ensemble form of :func:`make_cov_block_exchange`.

    ``exchange(h_blk, u_blk, t) -> (h_blk, u_blk, sym_sn, sym_we)`` over
    member-batched local blocks ``(B, 1, m_loc, m_loc)`` /
    ``(2, B, 1, m_loc, m_loc)`` — ``jax.vmap`` of the single-member
    block exchange, so every intra-panel neighbor shift AND cube-edge
    schedule stage issues ONE ``ppermute`` carrying all members' strips
    stacked ``(B, 3, halo, n_loc)``.  Per-member ghosts/seam normals are
    bitwise the per-member loop's (the receive algebra vmaps
    elementwise); the collective launch count per ensemble step drops
    B-fold at unchanged per-member wire bytes — the block-mesh face of
    the batched-exchange design (see shard_cov.py's twin).
    """
    exchange1 = make_cov_block_exchange(program)
    return jax.vmap(exchange1, in_axes=(0, 1, None),
                    out_axes=(0, 1, 0, 0))


def make_block_corner_fill(program: CovBlockProgram):
    """``corner_fill(blk3, t) -> blk3`` — fill the four h x h ghost
    corners of a stacked ``(3, m_loc, m_loc)`` block (h, u_a, u_b) from
    the neighbors' edge-ghost strip end patches (see the corner-routing
    tables in :class:`CovBlockProgram`).  Requires the edge ghosts to be
    filled first; needed only by corner-reading stencils (the nu4
    Laplacians — the dimension-split advective stencils never look)."""
    n, h = program.n_loc, program.halo
    i0, i1 = h, h + n
    _, ax_y, ax_x = program.axis_names
    # Same intra-panel shift perms the main exchange uses (s >= 2 is
    # enforced by the stepper factory).
    fwd = [(i, i + 1) for i in range(program.s - 1)]
    bwd = [(i + 1, i) for i in range(program.s - 1)]

    def corner_fill(blk3, t):
        def tt(name):
            v = t[name]
            return v.reshape(v.shape[3:])

        ux = tt("corner_use_x")          # (4,) one-hot per corner
        uy = tt("corner_use_y")
        ua = tt("corner_use_avg")

        S = blk3[:, 0:h, i0:i1]
        N = blk3[:, i1:i1 + h, i0:i1]
        W = blk3[:, i0:i1, 0:h]
        E = blk3[:, i0:i1, i1:i1 + h]
        # E-ends of my S/N strips -> (ix+1)'s west corners, etc.
        rx_w = lax.ppermute(jnp.stack([S[:, :, n - h:],
                                       N[:, :, n - h:]]), ax_x, fwd)
        rx_e = lax.ppermute(jnp.stack([S[:, :, :h],
                                       N[:, :, :h]]), ax_x, bwd)
        ry_s = lax.ppermute(jnp.stack([W[:, n - h:, :],
                                       E[:, n - h:, :]]), ax_y, fwd)
        ry_n = lax.ppermute(jnp.stack([W[:, :h, :],
                                       E[:, :h, :]]), ax_y, bwd)

        # Face-local averages (the oracle's cube-corner treatment; same
        # formulas as ops.pallas.swe_cov._make_fill corners=True).
        a_sw = 0.5 * (blk3[:, 0:h, i0:i0 + 1] + blk3[:, i0:i0 + 1, 0:h])
        a_se = 0.5 * (blk3[:, 0:h, i1 - 1:i1] + blk3[:, i0:i0 + 1, i1:i1 + h])
        a_nw = 0.5 * (blk3[:, i1:i1 + h, i0:i0 + 1] + blk3[:, i1 - 1:i1, 0:h])
        a_ne = 0.5 * (blk3[:, i1:i1 + h, i1 - 1:i1]
                      + blk3[:, i1 - 1:i1, i1:i1 + h])

        cands = [
            (0, slice(0, h), slice(0, h), rx_w[0], ry_s[0], a_sw),
            (1, slice(0, h), slice(i1, i1 + h), rx_e[0], ry_s[1], a_se),
            (2, slice(i1, i1 + h), slice(0, h), rx_w[1], ry_n[0], a_nw),
            (3, slice(i1, i1 + h), slice(i1, i1 + h), rx_e[1], ry_n[1],
             a_ne),
        ]
        for c, rs, cs, xv, yv, av in cands:
            val = ux[c] * xv + uy[c] * yv + ua[c] * av
            blk3 = blk3.at[:, rs, cs].set(val)
        return blk3

    return corner_fill


def make_sharded_cov_block_stepper(model, setup, dt: float, overlap=None,
                                   temporal_block: int = 1,
                                   donate: bool = False):
    """``step(state, t) -> state`` for the covariant model on (6, s, s).

    State is the usual interior pytree ``{"h": (6, n, n),
    "u": (2, 6, n, n)}`` sharded over all three mesh axes.  ``nu4 > 0``
    runs the exchange-lap-exchange-lap del^4 structure of the face tier
    (shard_cov.py), with the Laplacians' corner ghosts delivered by
    :func:`make_block_corner_fill` (neighbor strip end-patches; cube
    corners averaged face-locally like the oracle).

    ``overlap`` (default: the setup's ``overlap_exchange`` flag): issue
    every neighbor/cube-edge ppermute first, run the interior-only RHS
    kernel on the block's ghost-free (n_loc-2h)^2 core while the
    collectives are in flight, and finish with the boundary-band pass
    (interior/band split of :mod:`jaxstream.ops.pallas.swe_cov`, same
    schedule as the face tier).  Requires ``n_loc > 2*halo``.

    ``temporal_block = k > 1``: k steps fused inside ONE shard_map body
    per call (``steps_per_call`` attribute set) — one SPMD dispatch per
    k steps, exchange data unchanged.  Exact by construction (same ops
    per step; XLA cross-step re-fusion moves single ulps, the same
    <= 1e-6 multi-step budget as the overlap split), unlike the face
    tier's deep-halo form: the block mesh's sub-panel seams would be
    exact under redundant recompute, but its cube-edge blocks carry the
    panel-seam O(d^2) continuation problem plus an along-edge widening
    of every deep strip into the neighbor blocks — the fused form keeps
    this tier in the bitwise-reference family instead (composes with
    ``overlap``, which already hides most of the per-stage latency).
    """
    if temporal_block < 1:
        raise ValueError(
            f"temporal_block must be >= 1, got {temporal_block}")
    grid = model.grid
    s = setup.sy
    if setup.mesh is None or setup.panel != 6 or setup.sy != setup.sx \
            or s < 2:
        raise ValueError(
            f"covariant block path needs a (panel=6, s, s) mesh with "
            f"s >= 2; got panel={setup.panel}, y={setup.sy}, x={setup.sx}"
        )
    if overlap is None:
        overlap = getattr(setup, "overlap_exchange", False)
    mesh = setup.mesh
    halo = grid.halo
    program = CovBlockProgram(grid, s)
    n_loc = program.n_loc
    exchange = make_cov_block_exchange(program)
    platform = getattr(mesh.devices.flat[0], "platform", "cpu")

    from ..ops.pallas.swe_cov import make_cov_rhs_pallas_local

    rhs_local = make_cov_rhs_pallas_local(
        n_loc, halo, float(grid.dalpha), float(grid.radius),
        model.gravity, model.omega, scheme=model.scheme,
        limiter=model.limiter, interpret=(platform != "tpu"),
    )
    if overlap:
        from ..ops.pallas.swe_cov import (make_cov_rhs_band_local,
                                          make_cov_rhs_interior_local)

        ex_start, ex_finish = make_cov_block_exchange_phases(program)
        rhs_interior = make_cov_rhs_interior_local(
            n_loc, halo, float(grid.dalpha), float(grid.radius),
            model.gravity, model.omega, scheme=model.scheme,
            limiter=model.limiter, interpret=(platform != "tpu"))
        rhs_band = make_cov_rhs_band_local(
            n_loc, halo, float(grid.dalpha), float(grid.radius),
            model.gravity, model.omega, scheme=model.scheme,
            limiter=model.limiter)

    axes = mesh.axis_names
    pstate = {"h": P(*axes), "u": P(None, *axes)}
    ptab = {k: P(axes[0], axes[1], axes[2])
            for k in program.tables}

    # Static per-block b: overlapping extended blocks cannot come from
    # plain sharding, so pre-slice them host-side into a (6, s, s,
    # m_loc, m_loc) table sharded like everything else.
    m_loc = n_loc + 2 * halo
    b_np = np.asarray(model.b_ext)
    b_blocks = np.zeros((6, s, s, m_loc, m_loc), np.float32)
    for iy in range(s):
        for ix in range(s):
            b_blocks[:, iy, ix] = b_np[
                :, iy * n_loc : iy * n_loc + m_loc,
                ix * n_loc : ix * n_loc + m_loc]
    b_blocks = jnp.asarray(b_blocks)

    def embed(x):
        pad = [(0, 0)] * (x.ndim - 2) + [(halo, halo), (halo, halo)]
        return jnp.pad(x, pad)

    nu4 = float(getattr(model, "nu4", 0.0))
    if nu4 != 0.0:
        from ..ops.pallas.swe_cov import lap_core

        corner_fill = make_block_corner_fill(program)

    def body(state, tabs, b_loc):
        fz = tabs["fz"].reshape(1, 1, 3)
        xr = tabs["xr"].reshape(1, m_loc)
        xfr = tabs["xfr"].reshape(1, m_loc)
        yc = tabs["yc"].reshape(m_loc, 1)
        yfc = tabs["yfc"].reshape(m_loc, 1)
        b_e = b_loc.reshape(1, m_loc, m_loc)

        def f(h_int, u_int):
            h_e = embed(h_int)
            u_e = embed(u_int)
            if overlap:
                # Wire first: every payload is a function of the
                # pre-exchange strips, so the interior kernel overlaps
                # all in-flight collectives; the band pass consumes the
                # received strips afterwards.
                phase = ex_start(h_e, u_e, tabs)
                i0, i1 = halo, halo + n_loc
                dh_c, du_c = rhs_interior(
                    fz, xr[:, i0:i1], xfr[:, i0:i1], yc[i0:i1],
                    yfc[i0:i1], h_int, u_int, b_e[:, i0:i1, i0:i1])
                h_e, u_e, ssn, swe = ex_finish(h_e, u_e, tabs, phase)
                dh, du = rhs_band(fz, xr, xfr, yc, yfc, h_e, u_e, b_e,
                                  ssn, swe, dh_c, du_c)
            else:
                h_e, u_e, ssn, swe = exchange(h_e, u_e, tabs)
                dh, du = rhs_local(fz, xr, xfr, yc, yfc, h_e, u_e, b_e,
                                   ssn, swe)
            if nu4 != 0.0:
                # del^4 = lap(lap(.)) with an exchanged refill between —
                # the face tier's structure (shard_cov.py), per-block
                # runtime coordinates, corners from the neighbor-patch
                # pass (lap of a covariant pair IS a covariant pair, so
                # the same exchange applies to l1).
                def lap3(he, ue):
                    blk3 = corner_fill(
                        jnp.concatenate([he, ue[:, 0]], axis=0), tabs)
                    lap = lambda a: lap_core(
                        xr, xfr, yc, yfc, a, n=n_loc, halo=halo,
                        d=float(grid.dalpha), radius=float(grid.radius))
                    return (lap(blk3[0])[None],
                            jnp.stack([lap(blk3[1])[None],
                                       lap(blk3[2])[None]]))

                l1h, l1u = lap3(h_e, u_e)
                l1h_e, l1u_e, _, _ = exchange(embed(l1h), embed(l1u),
                                              tabs)
                l2h, l2u = lap3(l1h_e, l1u_e)
                dh = dh - nu4 * l2h
                du = du - nu4 * l2u
            return dh, du

        for _ in range(temporal_block):
            state = ssprk3_sharded_body(f, state, dt)
        return state

    shard_body = shard_map(
        body, mesh=mesh,
        in_specs=(pstate, ptab, P(*axes)),
        out_specs=pstate,
        check_vma=False,
    )

    tables = {
        k: jax.device_put(v, NamedSharding(mesh, ptab[k]))
        for k, v in program.tables.items()
    }
    b_sh = jax.device_put(b_blocks, NamedSharding(mesh, P(*axes)))

    jitted = jax.jit(lambda state: shard_body(state, tables, b_sh),
                     donate_argnums=(0,) if donate else ())

    def step(state, t):
        del t
        return jitted(state)

    if temporal_block > 1:
        step.steps_per_call = temporal_block
    return step
