"""Run a whole model step inside ``shard_map`` (explicit-collective path).

This composes :mod:`jaxstream.parallel.shard_halo` with the model layer:
the full SSPRK3 step — ghost fills via ``lax.ppermute``, FV stencils on
local blocks — executes as one SPMD program over the ``('panel','y','x')``
mesh, under a single top-level ``jit``.  This is the "hand-scheduled
collectives preserving the reference's race-free staging" design
(SURVEY.md §2.6) as opposed to the GSPMD-inferred path used by default.

Mechanics: every face-indexed array the model owns (grid metric terms,
Coriolis, topography, ...) is passed into ``shard_map`` as a sharded
argument; inside, a shallow-copied model is rebound to the local shards
and its unchanged ``rhs`` runs on ``(..., 1, M, M)`` blocks — the numerics
code is identical between the single-device, GSPMD, and explicit paths
(one source of truth, three execution strategies).
"""

from __future__ import annotations

import copy
import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

from ..geometry.cubed_sphere import CubedSphereGrid
from ..stepping import SCHEMES
from .mesh import ShardingSetup
from .shard_halo import make_block_halo_program, make_shard_halo_program

__all__ = ["make_sharded_stepper", "make_stepper_for", "shard_params"]


def make_stepper_for(model, setup, example_state, dt: float,
                     scheme: str = "ssprk3", temporal_block: int = None,
                     ensemble: int = 0, donate: bool = False,
                     precision=None):
    """Dispatch on the config's ``use_shard_map`` flag.

    Explicit ppermute path when requested (and the mesh fits), otherwise
    the GSPMD path: plain ``jit`` over the model step — sharded inputs
    make XLA infer the collectives (the reference's implicit model).

    ``temporal_block = k > 1`` (default: the setup's) returns a stepper
    advancing k steps per call (its ``steps_per_call`` attribute says
    how many): the deep-halo blocked stepper on the covariant face tier
    (ONE 3*k*halo-deep exchange per block), exact k-step fusion
    elsewhere.  Callers that count steps must honor ``steps_per_call``.

    ``ensemble = B > 0``: the returned stepper advances a member-batched
    state (``{"h": (B, 6, n, n), "u": (2, B, 6, n, n)}``-layout) — the
    explicit covariant face tier uses the batched-exchange ensemble
    stepper (one ppermute per schedule stage for ALL members), the
    GSPMD path vmaps the model step over the member axis and lets XLA
    batch the inferred collectives.  ``donate=True`` donates the state
    carry at the top-level jit (callers must then treat each input
    state as consumed).

    ``precision`` (round 10): the per-stage dtype policy is wired for
    the single-device fused covariant stepper
    (``CovariantShallowWater.make_fused_step(precision=...)``, where it
    composes with temporal blocking, ensembles and donation); the
    steppers this dispatcher builds run the classic jnp RHS inside
    shard_map / GSPMD, which has no bf16 stage form — a non-f32 policy
    is rejected here with that pointer rather than silently ignored.
    The sharded tiers' 16-bit-strip *wire accounting* is available
    without a stepper change: ``scripts/comm_probe.py --strip-dtype
    bf16`` / ``comm_probe.temporal_block_plan(strip_dtype_bytes=2)``.
    """
    from ..ops.pallas.precision import resolve_stage_precision
    from ..plan import rules as plan_rules
    from ..plan.proof import attach_proof

    if resolve_stage_precision(precision) is not None:
        # One source of truth for the pointer prose: the plan-layer
        # rule table (the same rule plan_for rejects the config with,
        # statically, before any trace).
        plan_rules.fail("stage-policy-needs-fused")
    if temporal_block is None:
        k = 1 if setup is None else getattr(setup, "temporal_block", 1)
    else:
        k = temporal_block

    mesh = getattr(setup, "mesh", None)
    n_dev = int(mesh.devices.size) if mesh is not None else 1

    def _stamped(step, tier):
        return attach_proof(step, _args_plan(
            model, tier, overlap=bool(getattr(setup, "overlap_exchange",
                                              False)),
            temporal_block=k, ensemble=ensemble, scheme=scheme,
            num_devices=n_dev))

    if setup is not None and setup.use_shard_map:
        if hasattr(model, "exchange_u"):
            # Covariant formulation: its explicit paths carry the
            # rotation exchange + seam symmetrization as ppermute strips
            # and run the Pallas RHS kernel per device (SSPRK3 only) —
            # one face per device, or sub-panel blocks (tiles_per_edge
            # > 1) on the (6, s, s) mesh.
            from .shard_cov import (make_sharded_cov_ensemble_stepper,
                                    make_sharded_cov_stepper)
            from .shard_cov_block import make_sharded_cov_block_stepper

            blocked_mesh = (setup.panel == 6 and setup.sy == setup.sx
                            and setup.sy > 1)
            if scheme != "ssprk3":
                plan_rules.fail("explicit-cov-ssprk3", plan=None,
                                scheme=scheme)
            if ensemble:
                if setup.sy * setup.sx != 1:
                    plan_rules.fail("ensemble-face-tier")
                return _stamped(make_sharded_cov_ensemble_stepper(
                    model, setup, dt, ensemble, temporal_block=k,
                    donate=donate), "face")
            if blocked_mesh:
                return _stamped(make_sharded_cov_block_stepper(
                    model, setup, dt, temporal_block=k,
                    donate=donate), "face_block")
            return _stamped(make_sharded_cov_stepper(
                model, setup, dt, temporal_block=k, donate=donate),
                "face")
        if ensemble:
            plan_rules.fail("ensemble-needs-cov-or-gspmd")
        if k > 1:
            plan_rules.fail("temporal-block-cartesian")
        return _stamped(
            make_sharded_stepper(model, setup, example_state, dt,
                                 scheme), "cartesian_shard")
    single = setup is None or setup.mesh is None
    tier = "classic" if single else "gspmd"
    base = model.make_step(dt, scheme)
    if ensemble:
        # GSPMD/single-device ensemble: vmap the model step over the
        # member axis; XLA batches any inferred collectives across
        # members for free.  Layout rule (the ENSEMBLE_STATE_AXES
        # convention): vector fields ("u" covariant / "v" Cartesian)
        # keep their component axis first, member second; scalars lead
        # with the member axis.
        from ..stepping import blocked, vmap_ensemble

        axes = {kk: (1 if kk in ("u", "v") else 0)
                for kk in example_state}
        vstep = vmap_ensemble(base, axes)
        if k > 1:
            vstep = blocked(vstep, k, dt)
        jitted = jax.jit(vstep, donate_argnums=(0,) if donate else ())

        def step(y, t):
            return jitted(y, t)

        step.ensemble = int(ensemble)
        if k > 1:
            step.steps_per_call = k
        return _stamped(step, tier)
    if k > 1:
        # GSPMD path: exact k-step fusion under one jit — one dispatch
        # per block, collectives unchanged (XLA may still pipeline
        # across the fused steps).
        from ..stepping import blocked

        jitted = jax.jit(blocked(base, k, dt),
                         donate_argnums=(0,) if donate else ())

        def step(y, t):
            return jitted(y, t)

        step.steps_per_call = k
        return _stamped(step, tier)
    return _stamped(jax.jit(base, donate_argnums=(0,) if donate else ()),
                    tier)


def _args_plan(model, tier: str, overlap: bool, temporal_block: int,
               ensemble: int, scheme: str, num_devices: int):
    """A :class:`~jaxstream.plan.plan.CapabilityPlan` reconstructed
    from direct factory arguments (the proof-stamp source for callers
    that bypass ``plan_for``'s config resolution)."""
    from ..plan.plan import CapabilityPlan
    from ..plan.rules import normalize

    grid = getattr(model, "grid", None)
    return normalize(CapabilityPlan(
        tier=tier,
        n=getattr(grid, "n", 0), halo=getattr(grid, "halo", 2),
        scheme=scheme, overlap=overlap,
        temporal_block=max(1, temporal_block or 1),
        ensemble=max(1, int(ensemble or 1)),
        nu4=getattr(model, "nu4", 0.0) != 0.0,
        num_devices=num_devices,
        use_shard_map=tier in ("face", "face_block",
                               "cartesian_shard"),
        backend=getattr(model, "backend", "jnp") or "jnp",
        covariant=hasattr(model, "exchange_u")))


def _grid_arrays(grid: CubedSphereGrid):
    """jax.Array attributes of a grid (dense dataclass or lazy plain class)."""
    if dataclasses.is_dataclass(grid):
        names = [f.name for f in dataclasses.fields(grid)]
    else:  # LazyCubedSphereGrid stores 1-D coords + (3, 6, 1, 1) frames
        names = list(vars(grid))
    out = {}
    for name in names:
        v = getattr(grid, name)
        if isinstance(v, jax.Array):
            out[name] = v
    return out


def _rebind(obj, updates):
    """dataclasses.replace for dataclasses; copy+setattr otherwise."""
    if dataclasses.is_dataclass(obj):
        return dataclasses.replace(obj, **updates)
    new = copy.copy(obj)
    for k, v in updates.items():
        setattr(new, k, v)
    return new


def _face_spec(a) -> P:
    """PartitionSpec for an array whose trailing axes are (6, ny, nx)."""
    if a.ndim <= 1:  # 1-D coordinate vectors (lazy grid): replicate
        return P(*((None,) * a.ndim))
    if a.ndim == 2:  # (6, 4) per-device parameter tables
        return P("panel", None)
    return P(*((None,) * (a.ndim - 3) + ("panel", "y", "x")))


def shard_params(setup: ShardingSetup, tree):
    """device_put a pytree of face-axis arrays with P('panel', ...)."""
    mesh = setup.mesh
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(mesh, _face_spec(a))), tree
    )


def _to_blocks(a, n_loc: int, halo: int, s: int):
    """Extended ``(..., 6, M, M)`` -> per-device ``(..., 6, s, s, m_loc,
    m_loc)`` blocks (overlapping halo slices — NamedSharding cannot
    express the overlap, so the blocks are materialized host-side once at
    setup; they are static geometry, not per-step state)."""
    m_loc = n_loc + 2 * halo
    return jnp.stack([
        jnp.stack([
            a[..., by * n_loc : by * n_loc + m_loc,
              bx * n_loc : bx * n_loc + m_loc]
            for bx in range(s)
        ], axis=-3)
        for by in range(s)
    ], axis=-4)


def _is_extended(a, m: int) -> bool:
    return a.ndim >= 2 and a.shape[-2:] == (m, m)


def make_sharded_stepper(model, setup: ShardingSetup, example_state,
                         dt: float, scheme: str = "ssprk3"):
    """Build ``step(state, t) -> state`` running fully inside shard_map.

    Mesh shapes supported: panel axis of size 6 with a square ``s x s``
    sub-panel block grid (``sy == sx == s``, ``n % s == 0``) — ``s = 1``
    is the flagship one-face-per-device layout, ``s > 1`` the reference's
    planned ``tiles_per_edge`` scaling run through the explicit
    block-halo program.  State arrays are the usual interior ``(6, n, n)``
    / ``(3, 6, n, n)`` pytrees sharded over (panel, y, x).
    ``example_state`` is only read for its tree structure/ranks.
    """
    grid = model.grid
    if hasattr(model, "exchange_u"):
        raise ValueError(
            "this explicit shard_map path only rebinds the scalar/Cartesian "
            "exchanger; covariant-component models (exchange_u) use "
            "jaxstream.parallel.shard_cov.make_sharded_cov_stepper (the "
            "make_stepper_for dispatcher picks it automatically), or the "
            "GSPMD path via parallelization.use_shard_map: false."
        )
    if (setup.mesh is None or setup.panel != 6 or setup.sy != setup.sx
            or grid.n % setup.sy):
        raise ValueError(
            f"explicit shard_map path needs mesh (panel=6, y=s, x=s) with "
            f"s dividing n={grid.n}; got panel={setup.panel}, y={setup.sy}, "
            f"x={setup.sx}. Use the GSPMD path (jax.jit over NamedSharding) "
            f"for other layouts."
        )
    mesh = setup.mesh
    s = setup.sy
    blocked = s > 1
    if blocked:
        if not dataclasses.is_dataclass(grid):
            raise ValueError(
                "block-mesh explicit path needs an eager CubedSphereGrid "
                "(metrics='eager'); lazy grids are only wired for s=1."
            )
        n_loc = grid.n // s
        program, local_exchange = make_block_halo_program(
            grid.n, grid.halo, s
        )
    else:
        n_loc = grid.n
        program, local_exchange = make_shard_halo_program(grid.n, grid.halo)
    m_ext = grid.m

    def pack(a):
        """Array + its PartitionSpec, block-slicing extended arrays."""
        if blocked and _is_extended(a, m_ext):
            blocks = _to_blocks(a, n_loc, grid.halo, s)
            spec = P(*((None,) * (blocks.ndim - 5)
                       + ("panel", "y", "x", None, None)))
            return blocks, spec
        return a, _face_spec(a)

    garrs = _grid_arrays(grid)
    aux = {k: v for k, v in vars(model).items()
           if isinstance(v, jax.Array) and v.ndim >= 3}
    packed = {
        "grid": {k: pack(v) for k, v in garrs.items()},
        "aux": {k: pack(v) for k, v in aux.items()},
    }
    params = {g: {k: v[0] for k, v in d.items()} for g, d in packed.items()}
    specs = {g: {k: v[1] for k, v in d.items()} for g, d in packed.items()}
    params["halo"] = dict(program.params)
    specs["halo"] = {
        k: (P("panel", "y", "x", None) if blocked else P("panel", None))
        for k in params["halo"]
    }
    params = {
        g: {k: jax.device_put(v, NamedSharding(mesh, specs[g][k]))
            for k, v in d.items()}
        for g, d in params.items()
    }
    stepper = SCHEMES[scheme]

    def unblock(a):
        # (..., 1, 1, 1, m_loc, m_loc) -> (..., 1, m_loc, m_loc)
        return a.reshape(a.shape[:-5] + (1,) + a.shape[-2:])

    def local_step(p, state, t):
        updates = {}
        for k, v in p["grid"].items():
            updates[k] = unblock(v) if (blocked and v.ndim >= 5
                                        and v.shape[-2] == n_loc + 2 * grid.halo
                                        and v.shape[-4] == 1) else v
        if blocked:
            grid_l = _rebind(grid, dict(updates, n=n_loc))
        else:
            grid_l = _rebind(grid, updates)
        m = copy.copy(model)
        m.grid = grid_l
        # Inside shard_map the RHS runs on (1, m_loc, m_loc) local blocks;
        # the 6-face Pallas kernel doesn't apply — use the jnp path (the
        # parity oracle, numerics-identical).
        m._pallas_rhs = None
        m_loc = n_loc + 2 * grid.halo
        for k, v in p["aux"].items():
            setattr(m, k, unblock(v) if (blocked and v.ndim >= 5
                                         and v.shape[-2] == m_loc
                                         and v.shape[-4] == 1) else v)
        if blocked:
            es, rs, ac = (p["halo"]["edge_sel"], p["halo"]["rev_sel"],
                          p["halo"]["active"])
            m.exchange = lambda f: local_exchange(f, es, rs, ac)
        else:
            es, rs = p["halo"]["edge_sel"], p["halo"]["rev_sel"]
            m.exchange = lambda f: local_exchange(f, es, rs)
        return stepper(m.rhs, state, t, dt)

    state_specs = jax.tree_util.tree_map(_face_spec, example_state)
    in_specs = (specs, state_specs, P())

    smapped = shard_map(
        local_step, mesh=mesh, in_specs=in_specs, out_specs=state_specs,
        check_vma=False,
    )

    @jax.jit
    def step(state, t):
        return smapped(params, state, t)

    return step
