"""Run a whole model step inside ``shard_map`` (explicit-collective path).

This composes :mod:`jaxstream.parallel.shard_halo` with the model layer:
the full SSPRK3 step — ghost fills via ``lax.ppermute``, FV stencils on
local blocks — executes as one SPMD program over the ``('panel','y','x')``
mesh, under a single top-level ``jit``.  This is the "hand-scheduled
collectives preserving the reference's race-free staging" design
(SURVEY.md §2.6) as opposed to the GSPMD-inferred path used by default.

Mechanics: every face-indexed array the model owns (grid metric terms,
Coriolis, topography, ...) is passed into ``shard_map`` as a sharded
argument; inside, a shallow-copied model is rebound to the local shards
and its unchanged ``rhs`` runs on ``(..., 1, M, M)`` blocks — the numerics
code is identical between the single-device, GSPMD, and explicit paths
(one source of truth, three execution strategies).
"""

from __future__ import annotations

import copy
import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..geometry.cubed_sphere import CubedSphereGrid
from ..stepping import SCHEMES
from .mesh import ShardingSetup
from .shard_halo import make_shard_halo_program

__all__ = ["make_sharded_stepper", "make_stepper_for", "shard_params"]


def make_stepper_for(model, setup, example_state, dt: float,
                     scheme: str = "ssprk3"):
    """Dispatch on the config's ``use_shard_map`` flag.

    Explicit ppermute path when requested (and the mesh fits), otherwise
    the GSPMD path: plain ``jit`` over the model step — sharded inputs
    make XLA infer the collectives (the reference's implicit model).
    """
    if setup is not None and setup.use_shard_map:
        return make_sharded_stepper(model, setup, example_state, dt, scheme)
    return jax.jit(model.make_step(dt, scheme))


def _grid_arrays(grid: CubedSphereGrid):
    """jax.Array attributes of a grid (dense dataclass or lazy plain class)."""
    if dataclasses.is_dataclass(grid):
        names = [f.name for f in dataclasses.fields(grid)]
    else:  # LazyCubedSphereGrid stores 1-D coords + (3, 6, 1, 1) frames
        names = list(vars(grid))
    out = {}
    for name in names:
        v = getattr(grid, name)
        if isinstance(v, jax.Array):
            out[name] = v
    return out


def _rebind(obj, updates):
    """dataclasses.replace for dataclasses; copy+setattr otherwise."""
    if dataclasses.is_dataclass(obj):
        return dataclasses.replace(obj, **updates)
    new = copy.copy(obj)
    for k, v in updates.items():
        setattr(new, k, v)
    return new


def _face_spec(a) -> P:
    """PartitionSpec for an array whose trailing axes are (6, ny, nx)."""
    if a.ndim <= 1:  # 1-D coordinate vectors (lazy grid): replicate
        return P(*((None,) * a.ndim))
    if a.ndim == 2:  # (6, 4) per-device parameter tables
        return P("panel", None)
    return P(*((None,) * (a.ndim - 3) + ("panel", "y", "x")))


def shard_params(setup: ShardingSetup, tree):
    """device_put a pytree of face-axis arrays with P('panel', ...)."""
    mesh = setup.mesh
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(mesh, _face_spec(a))), tree
    )


def make_sharded_stepper(model, setup: ShardingSetup, example_state,
                         dt: float, scheme: str = "ssprk3"):
    """Build ``step(state, t) -> state`` running fully inside shard_map.

    Requires the explicit-path mesh shape: panel axis of size 6, one face
    per device (``sy = sx = 1``); state arrays are the usual interior
    ``(6, n, n)`` / ``(3, 6, n, n)`` pytrees sharded over 'panel'.
    ``example_state`` is only read for its tree structure/ranks.
    """
    if setup.mesh is None or setup.panel != 6 or setup.sy * setup.sx != 1:
        raise ValueError(
            f"explicit shard_map path needs mesh (panel=6, y=1, x=1); got "
            f"panel={setup.panel}, y={setup.sy}, x={setup.sx}. Use the "
            f"GSPMD path (jax.jit over NamedSharding) for other layouts."
        )
    mesh = setup.mesh
    grid = model.grid
    program, local_exchange = make_shard_halo_program(grid.n, grid.halo)

    garrs = _grid_arrays(grid)
    aux = {k: v for k, v in vars(model).items()
           if isinstance(v, jax.Array) and v.ndim >= 3}
    params = {"grid": garrs, "aux": aux, "halo": dict(program.params)}
    params = shard_params(setup, params)
    stepper = SCHEMES[scheme]

    def local_step(p, state, t):
        grid_l = _rebind(grid, p["grid"])
        m = copy.copy(model)
        m.grid = grid_l
        for k, v in p["aux"].items():
            setattr(m, k, v)
        es, rs = p["halo"]["edge_sel"], p["halo"]["rev_sel"]
        m.exchange = lambda f: local_exchange(f, es, rs)
        return stepper(m.rhs, state, t, dt)

    state_specs = jax.tree_util.tree_map(_face_spec, example_state)
    in_specs = (jax.tree_util.tree_map(_face_spec, params), state_specs, P())

    smapped = jax.shard_map(
        local_step, mesh=mesh, in_specs=in_specs, out_specs=state_specs,
        check_vma=False,
    )

    @jax.jit
    def step(state, t):
        return smapped(params, state, t)

    return step
