"""Measured dead-end implementations, kept for the record.

Each module here is a parity-tested negative experiment whose
write-up lives in DESIGN.md ("Failed/negative experiments"); tests
are opt-in (slow-marked).  Nothing imports from here at runtime.
"""
