"""EXPERIMENTAL: neighbor-read fused covariant stage (measured dead end).

Quarantined from :mod:`jaxstream.ops.pallas.swe_cov` (VERDICT r1 weak #7):
a documented negative experiment — measured 2.8x SLOWER than the
strip-router stepper on TPU v5e at C384 — kept because the design is
instructive and the trade may flip on chips with a different MXU-latency/
DMA-overhead balance (see the design banner below and DESIGN.md
"Failed/negative experiments").  Parity-tested (opt-in, slow-marked) in
tests/test_cov_swe.py::test_cov_nbr_step_parity.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import tpu_compiler_params

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    edge_pairs,
)
from ..ops.pallas.swe_cov import (
    _OUT_SIGN,
    _rotation_tables,
    rhs_core_cov,
)
from ..ops.pallas.swe_rhs import (
    FACE_AXES,
    _fast_frame,
    coord_rows,
    pick_recon,
)

__all__ = ["make_cov_stage_nbr", "make_fused_ssprk3_cov_nbr"]

# ---------------------------------------------------------------------------
# Neighbor-read fused stage: zero strip traffic, zero inter-stage router.
#
# EXPERIMENTAL ALTERNATIVE — measured SLOWER than the strip-router stepper
# on TPU v5e at C384 (870 vs 2020 steps/s): the in-kernel costs of the
# orientation workarounds (MXU flip-matmuls and transposes on the ghost
# critical path, 6-way pl.when branch bodies, full-array constant-block
# fetches) exceed the strip-router's small-XLA-op overhead they remove.
# Kept because the design is instructive and the trade may flip on chips
# with different MXU latency / DMA overhead ratios; parity-tested against
# the oracle (tests/test_cov_swe.py::test_cov_nbr_step_parity).
#
# Design: each stage kernel receives the full (6, M, M) state as
# *constant* VMEM blocks (index_map pinned to 0, so Mosaic fetches them
# once per launch) alongside the usual per-face blocks, and every face
# fills its own ghost ring directly from its neighbors' interior rows with
# static slices inside a 6-way pl.when branch.  The three
# Mosaic-unsupported data movements are replaced by supported ones:
#   * along-edge reversal -> matmul with the anti-identity on the MXU at
#     Precision.HIGHEST, which is bitwise-exact for a permutation matrix;
#   * W/E orientation     -> 2-D transpose (supported);
#   * depth reversal      -> static sublane re-concatenation (halo rows).
# The symmetrized panel-edge normal velocities are also computed in-kernel:
# both faces of an edge evaluate the identical expression on the identical
# operands (each can see both panels' data), so their edge fluxes agree
# bitwise and mass conservation is preserved without any cross-kernel
# communication.  The integration carry shrinks to plain {h, u} extended
# fields, and per-step HBM traffic is exactly the field reads/writes.
# ---------------------------------------------------------------------------


def _edge_metric_rows(xr, yc, n, halo, radius):
    """(m0, m1) closed-form inverse-metric rows at each edge's faces.

    Face-independent (the equiangular metric depends only on |X|, |Y|);
    the across-edge coordinate is exactly +-1 (X = tan(+-pi/4)) and the
    along-edge coordinate row is the same one the RHS uses.  Returns dict
    edge -> (m0_row, m1_row), canonical along-edge order as (1, n) rows,
    with the (iaa, iab) pair for W/E and (iab, ibb) for S/N, matching
    covariant_face_normal_velocity.
    """
    h0, h1 = halo, halo + n
    out = {}
    # W/E edges: x-face at X = -1 / +1, along-edge coord = Y (rows).
    for edge, xe in ((EDGE_W, -1.0), (EDGE_E, 1.0)):
        F = _fast_frame(jnp.full((1, 1), xe, jnp.float32), yc[h0:h1], radius)
        # (n, 1) columns -> transpose to (1, n) rows.
        out[edge] = (jnp.swapaxes(F["inv_aa"], 0, 1),
                     jnp.swapaxes(F["inv_ab"], 0, 1))
    # S/N edges: y-face at Y = -1 / +1, along-edge coord = X (cols).
    for edge, ye in ((EDGE_S, -1.0), (EDGE_N, 1.0)):
        F = _fast_frame(xr[:, h0:h1], jnp.full((1, 1), ye, jnp.float32),
                        radius)
        out[edge] = (F["inv_ab"], F["inv_bb"])
    return out


def _depth_flip(strip, halo):
    """Reverse the (sublane) depth axis of a (halo, n) strip, statically."""
    return jnp.concatenate([strip[k:k + 1] for k in reversed(range(halo))],
                           axis=0)


def _nbr_tables(grid):
    """(T_sn_full, T_we_full, P_rev) for the neighbor-read kernels.

    Placed-layout rotation tables — (4, 6, 2, halo, n) for S/N ghost
    blocks and (4, 6, 2, n, halo) for W/E — derived from the canonical
    :func:`_rotation_tables` by the ``place_strip`` transforms, plus the
    (n, n) anti-identity used for exact MXU reversals.
    """
    Tc = _rotation_tables(grid)                     # (4, 6, 4, halo, n)
    t_sn = jnp.stack([jnp.flip(Tc[:, :, EDGE_S], axis=-2),
                      Tc[:, :, EDGE_N]], axis=2)    # (4, 6, 2, halo, n)
    t_we = jnp.stack([
        jnp.swapaxes(jnp.flip(Tc[:, :, EDGE_W], axis=-2), -1, -2),
        jnp.swapaxes(Tc[:, :, EDGE_E], -1, -2),
    ], axis=2)                                      # (4, 6, 2, n, halo)
    return (t_sn, t_we,
            jnp.asarray(np.eye(grid.n, dtype=np.float32)[::-1]))


def make_cov_stage_nbr(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    a: float,
    b: float,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
    tables=None,
):
    """One neighbor-read fused covariant RK stage (see section banner).

    ``a == 0``: ``stage(hc, uc, b_ext) -> (h, u)``; else
    ``stage(h0, u0, hc, uc, b_ext) -> (h, u)``.  All fields extended;
    output ghosts are finite but stale (next stage refills in-kernel).
    ``tables`` is the optional ``(T_sn_full, T_we_full, P_rev)`` triple so
    the stepper builds the rotation tables once for all three stages.
    """
    n, halo = grid.n, grid.halo
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    g_dt = b * dt
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)
    with_y0 = a != 0.0
    h = halo

    adj = build_connectivity()
    pair_of = {}
    for link, back in edge_pairs(adj):
        pair_of[(link.face, link.edge)] = (link, back, True)
        pair_of[(back.face, back.edge)] = (link, back, False)

    if tables is None:
        tables = _nbr_tables(grid)
    T_sn_full, T_we_full, P_rev = tables

    HIGH = jax.lax.Precision.HIGHEST

    def lane_flip(strip, p_ref):
        """Exact along-edge reversal of a (k, n) strip on the MXU."""
        return jax.lax.dot_general(
            strip, p_ref[:], (((1,), (0,)), ((), ())),
            precision=HIGH, preferred_element_type=jnp.float32)

    def raw_block(ref, face, edge, lead=()):
        """Neighbor ``face``'s interior boundary block for ``edge``."""
        if edge == EDGE_S:
            return ref[lead + (face, slice(i0, i0 + h), slice(i0, i1))]
        if edge == EDGE_N:
            return ref[lead + (face, slice(i1 - h, i1), slice(i0, i1))]
        if edge == EDGE_W:
            return ref[lead + (face, slice(i0, i1), slice(i0, i0 + h))]
        return ref[lead + (face, slice(i0, i1), slice(i1 - h, i1))]

    def canon_block(blk, edge):
        """Raw boundary block -> canonical (halo, n), depth 0 nearest."""
        if edge == EDGE_S:
            return blk
        if edge == EDGE_N:
            return _depth_flip(blk, h)
        t = jnp.swapaxes(blk, 0, 1)          # (halo, n), depth = cols
        if edge == EDGE_W:
            return t
        return _depth_flip(t, h)             # E: nearest is the last col

    def place_block(strip, edge):
        """Canonical (halo, n) -> the local ghost block's layout."""
        if edge == EDGE_S:
            return _depth_flip(strip, h)
        if edge == EDGE_N:
            return strip
        if edge == EDGE_W:
            return jnp.swapaxes(_depth_flip(strip, h), 0, 1)
        return jnp.swapaxes(strip, 0, 1)

    def ghost_canonical(ref, f, e, p_ref, lead=()):
        """Canonical-(halo, n) ghost data for face ``f``/edge ``e``."""
        link = adj[f][e]
        c = canon_block(raw_block(ref, link.nbr_face, link.nbr_edge,
                                  lead=lead), link.nbr_edge)
        if link.reversed_:
            c = lane_flip(c, p_ref)
        return c

    def store_ghost(scratch, e, placed):
        if e == EDGE_S:
            scratch[0:h, i0:i1] = placed
        elif e == EDGE_N:
            scratch[i1:i1 + h, i0:i1] = placed
        elif e == EDGE_W:
            scratch[i0:i1, 0:h] = placed
        else:
            scratch[i0:i1, i1:i1 + h] = placed

    def t_rows_adj(tsn_ref, twe_ref, f, e, j):
        """(1, n) T[i*2+j] rotation row at face f / edge e's adjacent
        ghost slot, canonical along order."""
        if e == EDGE_S:
            return tsn_ref[j, f, 0, h - 1:h, :]
        if e == EDGE_N:
            return tsn_ref[j, f, 1, 0:1, :]
        if e == EDGE_W:
            return jnp.swapaxes(twe_ref[j, f, 0, :, h - 1:h], 0, 1)
        return jnp.swapaxes(twe_ref[j, f, 1, :, 0:1], 0, 1)

    def int_adj_row(ref, f, e, lead=()):
        """(1, n) interior edge-adjacent row of face f, canonical order."""
        if e == EDGE_S:
            return ref[lead + (f, slice(i0, i0 + 1), slice(i0, i1))]
        if e == EDGE_N:
            return ref[lead + (f, slice(i1 - 1, i1), slice(i0, i1))]
        if e == EDGE_W:
            return jnp.swapaxes(
                ref[lead + (f, slice(i0, i1), slice(i0, i0 + 1))], 0, 1)
        return jnp.swapaxes(
            ref[lead + (f, slice(i0, i1), slice(i1 - 1, i1))], 0, 1)

    def ghost_adj_rows(u_ref, tsn_ref, twe_ref, f, e, p_ref):
        """Edge-adjacent ghost covariant components of face f in f's
        basis, canonical (1, n) rows — the other panel's adjacent
        interior row rotated through the adjacent-slot T entries."""
        link = adj[f][e]
        raws = []
        for comp in range(2):
            row = int_adj_row(u_ref, link.nbr_face, link.nbr_edge,
                              lead=(comp,))
            if link.reversed_:
                row = lane_flip(row, p_ref)
            raws.append(row)
        return [t_rows_adj(tsn_ref, twe_ref, f, e, 0) * raws[0]
                + t_rows_adj(tsn_ref, twe_ref, f, e, 1) * raws[1],
                t_rows_adj(tsn_ref, twe_ref, f, e, 2) * raws[0]
                + t_rows_adj(tsn_ref, twe_ref, f, e, 3) * raws[1]]

    def local_normal_rows(u_ref, tsn_ref, twe_ref, f, e, met, p_ref):
        """(1, n) face-f local edge-normal velocity, canonical order."""
        gi = ghost_adj_rows(u_ref, tsn_ref, twe_ref, f, e, p_ref)
        ii = [int_adj_row(u_ref, f, e, lead=(c,)) for c in range(2)]
        lower_is_ghost = e in (EDGE_S, EDGE_W)
        ub0 = 0.5 * ((gi[0] + ii[0]) if lower_is_ghost else (ii[0] + gi[0]))
        ub1 = 0.5 * ((gi[1] + ii[1]) if lower_is_ghost else (ii[1] + gi[1]))
        m0, m1 = met[e]
        return m0 * ub0 + m1 * ub1

    def kernel(*refs):
        if with_y0:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, p_ref,
             tsn_ref, twe_ref, h0_ref, u0_ref, hfull_ref, ufull_ref, b_ref,
             ho_ref, uo_ref, s_h, s_ua, s_ub, s_ssn, s_swe) = refs
        else:
            (fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref, p_ref,
             tsn_ref, twe_ref, hfull_ref, ufull_ref, b_ref,
             ho_ref, uo_ref, s_h, s_ua, s_ub, s_ssn, s_swe) = refs

        met = _edge_metric_rows(xr_ref[:], yc_ref[:], n, halo, radius)
        pid = pl.program_id(0)

        for f in range(6):
            @pl.when(pid == f)
            def _(f=f):
                # --- ghost fill, all-static slices for this face --------
                s_h[:] = hfull_ref[f]
                s_ua[:] = ufull_ref[0, f]
                s_ub[:] = ufull_ref[1, f]
                for e in range(4):
                    gh = ghost_canonical(hfull_ref, f, e, p_ref)
                    store_ghost(s_h, e, place_block(gh, e))
                    raw = [ghost_canonical(ufull_ref, f, e, p_ref,
                                           lead=(c,)) for c in range(2)]
                    # Full-depth T tables at this face's ghost slots,
                    # un-placed back to canonical (halo, n) layout
                    # (place/unplace are involutive per edge).
                    if e == EDGE_S:
                        Ts = [_depth_flip(tsn_ref[j, f, 0], h)
                              for j in range(4)]
                    elif e == EDGE_N:
                        Ts = [tsn_ref[j, f, 1] for j in range(4)]
                    elif e == EDGE_W:
                        Ts = [_depth_flip(jnp.swapaxes(twe_ref[j, f, 0],
                                                       0, 1), h)
                              for j in range(4)]
                    else:
                        Ts = [jnp.swapaxes(twe_ref[j, f, 1], 0, 1)
                              for j in range(4)]
                    ca = Ts[0] * raw[0] + Ts[1] * raw[1]
                    cb = Ts[2] * raw[0] + Ts[3] * raw[1]
                    store_ghost(s_ua, e, place_block(ca, e))
                    store_ghost(s_ub, e, place_block(cb, e))
                # --- symmetrized edge normals ---------------------------
                for e in range(4):
                    link, back, is_link = pair_of[(f, e)]
                    nl = local_normal_rows(ufull_ref, tsn_ref, twe_ref,
                                           link.face, link.edge, met, p_ref)
                    nb = local_normal_rows(ufull_ref, tsn_ref, twe_ref,
                                           back.face, back.edge, met, p_ref)
                    if link.reversed_:
                        nb = lane_flip(nb, p_ref)
                    out_a = jnp.float32(_OUT_SIGN[link.edge]) * nl
                    out_b = jnp.float32(_OUT_SIGN[back.edge]) * nb
                    avg = 0.5 * (out_a - out_b)
                    if is_link:
                        mine = jnp.float32(_OUT_SIGN[link.edge]) * avg
                    else:
                        mine = jnp.float32(_OUT_SIGN[back.edge]) * (-avg)
                        if link.reversed_:
                            mine = lane_flip(mine, p_ref)
                    if e == EDGE_S:
                        s_ssn[0:1, :] = mine
                    elif e == EDGE_N:
                        s_ssn[1:2, :] = mine
                    elif e == EDGE_W:
                        s_swe[:, 0:1] = jnp.swapaxes(mine, 0, 1)
                    else:
                        s_swe[:, 1:2] = jnp.swapaxes(mine, 0, 1)

        fz = (fz_ref[0, 0, 0], fz_ref[0, 0, 1], fz_ref[0, 0, 2])
        hf = s_h[:]
        ua = s_ua[:]
        ub = s_ub[:]
        dh, dua, dub = rhs_core_cov(
            fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
            hf, ua, ub, b_ref[0], s_ssn[:], s_swe[:],
            n=n, halo=halo, d=d, radius=radius,
            gravity=gravity, omega=omega, recon=recon,
        )

        fa = jnp.float32(a)
        fb = jnp.float32(b)
        fg = jnp.float32(g_dt)
        if with_y0:
            out_h = fa * h0_ref[0] + fb * hf
            out_u = [fa * u0_ref[i, 0] + fb * (ua if i == 0 else ub)
                     for i in range(2)]
        else:
            out_h = hf if b == 1.0 else fb * hf
            out_u = [ua, ub] if b == 1.0 else [fb * ua, fb * ub]

        ho_ref[0] = out_h
        ho_ref[0, i0:i1, i0:i1] = out_h[i0:i1, i0:i1] + fg * dh
        for i, tend in ((0, dua), (1, dub)):
            uo_ref[i, 0] = out_u[i]
            uo_ref[i, 0, i0:i1, i0:i1] = (out_u[i][i0:i1, i0:i1]
                                          + fg * tend)

    fz_spec = pl.BlockSpec((1, 1, 3), lambda f: (f, 0, 0),
                           memory_space=pltpu.SMEM)
    coord_specs = [
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, m), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, 1), lambda f: (0, 0), memory_space=pltpu.VMEM),
    ]
    p_spec = pl.BlockSpec((n, n), lambda f: (0, 0), memory_space=pltpu.VMEM)
    tsn_spec = pl.BlockSpec((4, 6, 2, h, n), lambda f: (0, 0, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    twe_spec = pl.BlockSpec((4, 6, 2, n, h), lambda f: (0, 0, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    h_blk = pl.BlockSpec((1, m, m), lambda f: (f, 0, 0),
                         memory_space=pltpu.VMEM)
    u_blk = pl.BlockSpec((2, 1, m, m), lambda f: (0, f, 0, 0),
                         memory_space=pltpu.VMEM)
    hfull_spec = pl.BlockSpec((6, m, m), lambda f: (0, 0, 0),
                              memory_space=pltpu.VMEM)
    ufull_spec = pl.BlockSpec((2, 6, m, m), lambda f: (0, 0, 0, 0),
                              memory_space=pltpu.VMEM)

    in_specs = [fz_spec] + coord_specs + [p_spec, tsn_spec, twe_spec]
    if with_y0:
        in_specs += [h_blk, u_blk]
    in_specs += [hfull_spec, ufull_spec, h_blk]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(6,),
            in_specs=in_specs,
            out_specs=[h_blk, u_blk],
            scratch_shapes=[
                pltpu.VMEM((m, m), jnp.float32),
                pltpu.VMEM((m, m), jnp.float32),
                pltpu.VMEM((m, m), jnp.float32),
                pltpu.VMEM((2, n), jnp.float32),
                pltpu.VMEM((n, 2), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, m, m), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, m, m), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    if with_y0:
        def stage(h0, u0, hc, uc, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              P_rev, T_sn_full, T_we_full,
                              h0, u0, hc, uc, b_ext))
    else:
        def stage(hc, uc, b_ext):
            return tuple(call(frames_z, x_row, xf_row, x_col, xf_col,
                              P_rev, T_sn_full, T_we_full, hc, uc, b_ext))
    return stage


def make_fused_ssprk3_cov_nbr(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """``step(y, t) -> y`` over plain extended state ``y = {h, u}``.

    Three neighbor-read stage kernels and nothing else — no strip carry,
    no inter-stage ops at all.
    """
    from ..ops.pallas.swe_step import SSPRK3_COEFFS

    tables = _nbr_tables(grid)
    mk = lambda a, b: make_cov_stage_nbr(
        grid, gravity, omega, dt, a, b,
        scheme=scheme, limiter=limiter, interpret=interpret, tables=tables,
    )
    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    stage1 = mk(a1, b1)
    stage2 = mk(a2, b2)
    stage3 = mk(a3, b3)

    def step(y, t):
        del t
        h0, u0 = y["h"], y["u"]
        h1, u1 = stage1(h0, u0, b_ext)
        h2, u2 = stage2(h0, u0, h1, u1, b_ext)
        h3, u3 = stage3(h0, u0, h2, u2, b_ext)
        return {"h": h3, "u": u3}

    return step
