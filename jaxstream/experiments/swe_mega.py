"""Whole-step fused covariant SWE kernel: SSPRK3 in ONE pallas_call.

The compact stepper (swe_cov.py) is three stage kernels + three XLA
routes; between stages the full state makes an HBM round trip and the
RK prior y0 is re-read per stage — ~75 MB/step of traffic whose only
purpose is crossing kernel boundaries.  Here the entire step is one
``pallas_call`` with grid ``(3 stages x (1 router + 6 faces),)``: y0
and b are fetched once as pinned full blocks, the evolving state and
its boundary strips live in VMEM scratch across the whole step, and
HBM sees one read of the carry and one write of the result.

The inter-stage router runs as a dedicated grid step.  Its data
movements (static row-gather of strips, along-edge reversals) are
expressed as one-hot / anti-identity matmuls at ``Precision.HIGHEST``
— bitwise-exact permutations on the MXU (the trick validated by the
neighbor-read experiment in swe_cov.py) — followed by the same
rotation multiply-adds and pair-symmetrization algebra as the XLA
routers (reversal selection via exact 0/1 masks), so the ghosts are
bitwise-identical to :func:`make_cov_strip_router_split` and the whole
step to the compact stepper (tested).

Per-stage variation (RK combine coefficients) is data in SMEM indexed
by the stage id; the program is uniform over the grid apart from one
``pl.when`` router/face branch.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import tpu_compiler_params

from ..geometry.connectivity import (
    EDGE_E,
    EDGE_N,
    EDGE_S,
    EDGE_W,
    build_connectivity,
    edge_pairs,
)
from ..geometry.cubed_sphere import FACE_AXES
from ..ops.pallas.swe_cov import (
    _EORDER,
    _OUT_SIGN,
    _SLOT,
    _rotation_tables,
    rhs_core_cov,
)
from ..ops.pallas.swe_rhs import coord_rows, pick_recon

__all__ = ["make_fused_ssprk3_cov_mega"]

HIGH = jax.lax.Precision.HIGHEST


def _gather_matrix(grid):
    """One-hot gather: every router input row as P @ [S ; S J].

    ``S`` is the flat strip tensor (sn rows then weT rows, 12*6h rows);
    outputs are the placed S/N ghost rows, placed W/E (row-form) ghost
    rows, and the interior boundary-adjacent u rows — the same row set
    as make_cov_strip_router_split's gather, as a dense 0/1 matrix.
    """
    n, halo = grid.n, grid.halo
    h = halo
    adj = build_connectivity()
    F = 2 * 6 * 6 * h

    def src_row(fi, g, e, depth):
        kr = depth if e in (EDGE_S, EDGE_W) else h - 1 - depth
        sec = 0 if e in (EDGE_S, EDGE_N) else 6 * 6 * h
        pair = 0 if e in (EDGE_S, EDGE_W) else h
        return sec + g * 6 * h + fi * 2 * h + pair + kr

    rows = []

    def ghost_rows(edges):
        for fi in range(3):
            for f in range(6):
                for e in edges:
                    link = adj[f][e]
                    for k in range(h):
                        dep = (h - 1 - k) if e in (EDGE_S, EDGE_W) else k
                        rows.append((src_row(fi, link.nbr_face,
                                             link.nbr_edge, dep),
                                     link.reversed_))

    ghost_rows((EDGE_S, EDGE_N))
    n_sn = len(rows)
    ghost_rows((EDGE_W, EDGE_E))
    n_we = len(rows) - n_sn
    for c in range(2):
        for f in range(6):
            for e in _EORDER:
                rows.append((src_row(1 + c, f, e, 0), False))

    P = np.zeros((len(rows), 2 * F), np.float32)
    for i, (r, rev) in enumerate(rows):
        P[i, r + (F if rev else 0)] = 1.0
    return P, n_sn, n_we


def _sym_mats():
    """Selection/scatter matrices + masks of the pair symmetrization."""
    adj = build_connectivity()
    links = [lk for lk, _ in edge_pairs(adj)]
    backs = [bk for _, bk in edge_pairs(adj)]
    SEL_A = np.zeros((12, 24), np.float32)
    SEL_B = np.zeros((12, 24), np.float32)
    SC_A = np.zeros((24, 12), np.float32)
    SC_B = np.zeros((24, 12), np.float32)
    sga = np.zeros((12, 1), np.float32)
    sgb = np.zeros((12, 1), np.float32)
    rev = np.zeros((12, 1), np.float32)
    for i, (lk, bk) in enumerate(zip(links, backs)):
        SEL_A[i, lk.face * 4 + _SLOT[lk.edge]] = 1.0
        SEL_B[i, bk.face * 4 + _SLOT[bk.edge]] = 1.0
        SC_A[lk.face * 4 + _SLOT[lk.edge], i] = 1.0
        SC_B[bk.face * 4 + _SLOT[bk.edge], i] = 1.0
        sga[i] = _OUT_SIGN[lk.edge]
        sgb[i] = _OUT_SIGN[bk.edge]
        rev[i] = 1.0 if lk.reversed_ else 0.0
    return SEL_A, SEL_B, SC_A, SC_B, sga, sgb, rev


def make_fused_ssprk3_cov_mega(
    grid,
    gravity: float,
    omega: float,
    dt: float,
    b_ext,
    scheme: str = "plr",
    limiter: str = "mc",
    interpret: bool = False,
):
    """``step(y, t) -> y`` over the compact split-strip carry, one kernel.

    Same carry and bitwise-identical results as the compact stepper
    (tested); the difference is purely where data lives between stages.
    """
    from ..ops.pallas.swe_step import SSPRK3_COEFFS

    n, halo = grid.n, grid.halo
    h = halo
    m = n + 2 * halo
    i0, i1 = halo, halo + n
    d = float(grid.dalpha)
    radius = float(grid.radius)
    recon = pick_recon(scheme, halo, n, limiter)
    x_row, xf_row, x_col, xf_col, _ = coord_rows(n, halo)
    frames_z = jnp.asarray(np.asarray(FACE_AXES)[:, None, :, 2], jnp.float32)

    (a1, b1), (a2, b2), (a3, b3) = SSPRK3_COEFFS
    AB = jnp.asarray([[0.0, 1.0, b1 * dt],
                      [a2, b2, b2 * dt],
                      [a3, b3, b3 * dt]], jnp.float32)

    P_np, n_sn, n_we = _gather_matrix(grid)
    P = jnp.asarray(P_np)
    F = P_np.shape[1] // 2
    J = jnp.asarray(np.eye(n, dtype=np.float32)[::-1])

    Tc = np.asarray(_rotation_tables(grid))
    T_sn = jnp.asarray(np.stack(
        [Tc[:, :, EDGE_S, ::-1], Tc[:, :, EDGE_N]], axis=2))
    T_we = jnp.asarray(np.stack(
        [Tc[:, :, EDGE_W, ::-1], Tc[:, :, EDGE_E]], axis=2))

    mats = [jnp.asarray(x) for x in _sym_mats()]
    SEL_A, SEL_B, SC_A, SC_B, sga, sgb, rev = mats

    M0 = jnp.stack([jnp.asarray({
        EDGE_W: grid.ginv_aa_xf[0, i0:i1, i0],
        EDGE_E: grid.ginv_aa_xf[0, i0:i1, i1],
        EDGE_S: grid.ginv_ab_yf[0, i0, i0:i1],
        EDGE_N: grid.ginv_ab_yf[0, i1, i0:i1]}[e]) for e in _EORDER])
    M1 = jnp.stack([jnp.asarray({
        EDGE_W: grid.ginv_ab_xf[0, i0:i1, i0],
        EDGE_E: grid.ginv_ab_xf[0, i0:i1, i1],
        EDGE_S: grid.ginv_bb_yf[0, i0, i0:i1],
        EDGE_N: grid.ginv_bb_yf[0, i1, i0:i1]}[e]) for e in _EORDER])

    SNR = 6 * 6 * h          # rows in the sn section of flat S

    def dot(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   precision=HIGH,
                                   preferred_element_type=jnp.float32)

    def kernel(AB_ref, fz_ref, xr_ref, xfr_ref, yc_ref, yfc_ref,
               y0h_ref, y0u_ref, sn_in_ref, we_in_ref, b_ref,
               P_ref, J_ref, Tsn_ref, Twe_ref,
               SELA_ref, SELB_ref, SCA_ref, SCB_ref,
               sga_ref, sgb_ref, rev_ref, M0_ref, M1_ref,
               ho_ref, uo_ref, sno_ref, weo_ref,
               cur_h, cur_u, sn_s, we_s, gsn_s, gwe_s, w0, w1, w2):
        p = pl.program_id(0)
        stage = p // 7
        sub = p % 7

        @pl.when(sub == 0)
        def _router():
            @pl.when(p == 0)
            def _init():
                cur_h[:] = y0h_ref[:]
                cur_u[:] = y0u_ref[:]
                sn_s[:] = sn_in_ref[:]
                we_s[:] = we_in_ref[:]

            S = jnp.concatenate(
                [sn_s[:].reshape(SNR, n),
                 jnp.swapaxes(we_s[:], 1, 2).reshape(SNR, n)], axis=0)
            S_all = jnp.concatenate([S, dot(S, J_ref[:])], axis=0)
            rows = dot(P_ref[:], S_all)
            C_sn = rows[:n_sn].reshape(3, 6, 2, h, n)
            C_we = rows[n_sn:n_sn + n_we].reshape(3, 6, 2, h, n)
            I_u = rows[n_sn + n_we:].reshape(2, 6, 4, n)

            Tsn = Tsn_ref[:]
            Twe = Twe_ref[:]
            G_sn = [C_sn[0],
                    Tsn[0] * C_sn[1] + Tsn[1] * C_sn[2],
                    Tsn[2] * C_sn[1] + Tsn[3] * C_sn[2]]
            G_we = [C_we[0],
                    Twe[0] * C_we[1] + Twe[1] * C_we[2],
                    Twe[2] * C_we[1] + Twe[3] * C_we[2]]

            ka, kb = h - 1, 0          # placed edge-adjacent rows (S/W, N/E)
            gadj_a = jnp.stack(
                [G_sn[1][:, 0, ka], G_sn[1][:, 1, kb],
                 G_we[1][:, 0, ka], G_we[1][:, 1, kb]], axis=1)
            gadj_b = jnp.stack(
                [G_sn[2][:, 0, ka], G_sn[2][:, 1, kb],
                 G_we[2][:, 0, ka], G_we[2][:, 1, kb]], axis=1)
            ubar0 = 0.5 * (I_u[0] + gadj_a)
            ubar1 = 0.5 * (I_u[1] + gadj_b)
            L = (M0_ref[:][None] * ubar0 + M1_ref[:][None] * ubar1
                 ).reshape(24, n)

            la = dot(SELA_ref[:], L)
            lb = dot(SELB_ref[:], L)
            rv = rev_ref[:]
            one = jnp.float32(1.0)
            lb = rv * dot(lb, J_ref[:]) + (one - rv) * lb
            avg = 0.5 * (sga_ref[:] * la - sgb_ref[:] * lb)
            na = sga_ref[:] * avg
            nb = sgb_ref[:] * (-avg)
            nb = rv * dot(nb, J_ref[:]) + (one - rv) * nb
            sym = (dot(SCA_ref[:], na) + dot(SCB_ref[:], nb)
                   ).reshape(6, 4, n)

            gsn_s[:] = jnp.concatenate(
                [jnp.concatenate([g.reshape(6, 2 * h, n) for g in G_sn],
                                 axis=1), sym[:, 0:2]], axis=1)
            gwe_s[:] = jnp.swapaxes(jnp.concatenate(
                [jnp.concatenate([g.reshape(6, 2 * h, n) for g in G_we],
                                 axis=1), sym[:, 2:4]], axis=1), 1, 2)

        @pl.when(sub > 0)
        def _face():
            f = sub - 1
            gsn = gsn_s[f]
            gwe = gwe_s[f]

            def fill(scratch, int_val, fi):
                scratch[i0:i1, i0:i1] = int_val
                scratch[0:h, i0:i1] = gsn[fi * 2 * h:fi * 2 * h + h]
                scratch[i1:i1 + h, i0:i1] = gsn[fi * 2 * h + h:
                                                (fi + 1) * 2 * h]
                scratch[i0:i1, 0:h] = gwe[:, fi * 2 * h:fi * 2 * h + h]
                scratch[i0:i1, i1:i1 + h] = gwe[:, fi * 2 * h + h:
                                                (fi + 1) * 2 * h]
                return scratch[:]

            hf = fill(w0, cur_h[f], 0)
            ua = fill(w1, cur_u[0, f], 1)
            ub = fill(w2, cur_u[1, f], 2)
            fz = (fz_ref[f, 0, 0], fz_ref[f, 0, 1], fz_ref[f, 0, 2])
            ssn = gsn[6 * h:6 * h + 2]
            swe = gwe[:, 6 * h:6 * h + 2]

            dh, dua, dub = rhs_core_cov(
                fz, xr_ref[:], xfr_ref[:], yc_ref[:], yfc_ref[:],
                hf, ua, ub, b_ref[f], ssn, swe,
                n=n, halo=halo, d=d, radius=radius,
                gravity=gravity, omega=omega, recon=recon,
            )

            A = AB_ref[stage, 0]
            B = AB_ref[stage, 1]
            C = AB_ref[stage, 2]

            def emit(y0_f, cur_ref, idx, tend, fi):
                int_new = (A * y0_f + B * cur_ref[idx]) + C * tend
                cur_ref[idx] = int_new
                sn_s[f, fi * 2 * h:fi * 2 * h + h] = int_new[0:h, :]
                sn_s[f, fi * 2 * h + h:(fi + 1) * 2 * h] = (
                    int_new[n - h:n, :])
                we_s[f, :, fi * 2 * h:fi * 2 * h + h] = int_new[:, 0:h]
                we_s[f, :, fi * 2 * h + h:(fi + 1) * 2 * h] = (
                    int_new[:, n - h:n])

            emit(y0h_ref[f], cur_h, f, dh, 0)
            emit(y0u_ref[0, f], cur_u, (0, f), dua, 1)
            emit(y0u_ref[1, f], cur_u, (1, f), dub, 2)

            @pl.when(p == 20)
            def _writeback():
                ho_ref[:] = cur_h[:]
                uo_ref[:] = cur_u[:]
                sno_ref[:] = sn_s[:]
                weo_ref[:] = we_s[:]

    def pin(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda p, _nd=nd: (0,) * _nd,
                            memory_space=pltpu.VMEM)

    G_rows = P_np.shape[0]
    in_specs = [
        pl.BlockSpec((3, 3), lambda p: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((6, 1, 3), lambda p: (0, 0, 0),
                     memory_space=pltpu.SMEM),
        pin((1, m)), pin((1, m)), pin((m, 1)), pin((m, 1)),
        pin((6, n, n)), pin((2, 6, n, n)),
        pin((6, 6 * h, n)), pin((6, n, 6 * h)),
        pin((6, m, m)),
        pin((G_rows, 2 * F)), pin((n, n)),
        pin((4, 6, 2, h, n)), pin((4, 6, 2, h, n)),
        pin((12, 24)), pin((12, 24)), pin((24, 12)), pin((24, 12)),
        pin((12, 1)), pin((12, 1)), pin((12, 1)),
        pin((4, n)), pin((4, n)),
    ]
    out_specs = [
        pin((6, n, n)), pin((2, 6, n, n)),
        pin((6, 6 * h, n)), pin((6, n, 6 * h)),
    ]

    call = pl.pallas_call(
        kernel,
        grid_spec=pl.GridSpec(
            grid=(21,),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((6, n, n), jnp.float32),
                pltpu.VMEM((2, 6, n, n), jnp.float32),
                pltpu.VMEM((6, 6 * h, n), jnp.float32),
                pltpu.VMEM((6, n, 6 * h), jnp.float32),
                pltpu.VMEM((6, 6 * h + 2, n), jnp.float32),
                pltpu.VMEM((6, n, 6 * h + 2), jnp.float32),
                pltpu.VMEM((m, m), jnp.float32),
                pltpu.VMEM((m, m), jnp.float32),
                pltpu.VMEM((m, m), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((2, 6, n, n), jnp.float32),
            jax.ShapeDtypeStruct((6, 6 * h, n), jnp.float32),
            jax.ShapeDtypeStruct((6, n, 6 * h), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=120 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def step(y, t):
        del t
        h3, u3, sn3, we3 = call(
            AB, frames_z, x_row, xf_row, x_col, xf_col,
            y["h"], y["u"], y["strips_sn"], y["strips_we"], b_ext,
            P, J, T_sn, T_we, SEL_A, SEL_B, SC_A, SC_B,
            sga, sgb, rev, M0, M1)
        return {"h": h3, "u": u3, "strips_sn": sn3, "strips_we": we3}

    return step
