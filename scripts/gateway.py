"""Network serving CLI: the asyncio gateway over EnsembleServer.

Usage::

    python scripts/gateway.py config.yaml [--host 127.0.0.1]
        [--port 8080] [--sink gateway.jsonl]
        [--autoscale-levels 1,4,16] [--queue-high 4] [--queue-low 0]
        [--occ-low 0.5] [--patience 2] [--cooldown 2]
        [--run-seconds 0] [--profile-dir profiles/]

``config.yaml`` is the standard config surface (grid/time/physics/
model + the ``serve:`` block).  The process serves until SIGTERM or
SIGINT (or for ``--run-seconds``, for tests/demos), then drains
gracefully — admissions get 503 ``draining``, in-flight members run to
their final step, sinks flush — and prints exactly ONE JSON summary
line on stdout (everything else goes to stderr).

``--autoscale-levels`` enables live autoscaling: the levels must be a
subset of ``serve.buckets`` (every level maps to a warm executable, so
a resize never compiles); the policy watches queue depth + occupancy
at segment boundaries (jaxstream.loadgen.autoscale).

Endpoints: ``POST /v1/requests`` (NDJSON event stream), ``GET /v1/ws``
(the same protocol over WebSocket), ``/v1/health``, ``/v1/ready``,
``/v1/stats``, ``GET /v1/metrics`` (Prometheus text exposition) and
``POST /v1/profile`` (on-demand ``jax.profiler`` capture, enabled by
``--profile-dir``; typed 501 otherwise) — schema in docs/USAGE.md
"Network serving" and "Operator view".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _signals  # noqa: E402 — shared CLI signal-drain helper

log = _signals.log


def build_autoscale(args):
    if not args.autoscale_levels:
        return None
    from jaxstream.loadgen.autoscale import (AutoscaleController,
                                             AutoscalePolicy)

    levels = tuple(int(b) for b in args.autoscale_levels.split(",")
                   if b.strip())
    return AutoscaleController(AutoscalePolicy(
        levels=levels, queue_high=args.queue_high,
        queue_low=args.queue_low, occ_low=args.occ_low,
        patience=args.patience, cooldown=args.cooldown))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Serve scenario requests over HTTP/WebSocket "
                    "through the continuous-batching ensemble server.")
    ap.add_argument("config", help="server config YAML")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback)")
    ap.add_argument("--port", type=int, default=8080,
                    help="bind port (0 = ephemeral, printed to stderr)")
    ap.add_argument("--sink", default="",
                    help="gateway telemetry JSONL (per-request "
                         "'gateway' records)")
    ap.add_argument("--autoscale-levels", default="",
                    help="comma-separated bucket-cap ladder (subset of "
                         "serve.buckets); empty = autoscaling off")
    ap.add_argument("--queue-high", type=int, default=4)
    ap.add_argument("--queue-low", type=int, default=0)
    ap.add_argument("--occ-low", type=float, default=0.5)
    ap.add_argument("--patience", type=int, default=2)
    ap.add_argument("--cooldown", type=int, default=2)
    ap.add_argument("--run-seconds", type=float, default=0.0,
                    help="serve for N seconds then drain (0 = until "
                         "SIGTERM/SIGINT)")
    ap.add_argument("--profile-dir", default="",
                    help="enable POST /v1/profile: on-demand "
                         "jax.profiler captures land here (empty = "
                         "endpoint answers a typed 501)")
    ap.add_argument("--flight-dir", default="",
                    help="flight-recorder crash-bundle directory "
                         "(default: '<--sink>.flight' when --sink is "
                         "given, else off)")
    args = ap.parse_args(argv)

    import dataclasses

    from jaxstream.config import load_config
    from jaxstream.gateway import Gateway

    cfg = load_config(args.config)
    flight_dir = args.flight_dir or (
        args.sink + ".flight" if args.sink else "")
    if flight_dir:
        cfg = dataclasses.replace(
            cfg, observability=dataclasses.replace(
                cfg.observability, flight_dir=flight_dir))

    gw = Gateway(cfg, host=args.host, port=args.port,
                 autoscale=build_autoscale(args), sink=args.sink,
                 profile_dir=args.profile_dir)

    stop = threading.Event()

    def _drain(signame: str) -> None:
        # Commit the black box FIRST (gw.close's drain may take a
        # while; the bundle must exist even if the drain is cut short
        # by a second, harder signal).
        gw.server.flight_dump(reason=f"signal:{signame}")

    _signals.install_drain_handlers(stop, _drain, name="gateway")

    gw.start()
    log(f"gateway: serving on {gw.url} "
        f"(buckets {list(gw.server.buckets)}, warm "
        f"{gw.warm_compiles} executables)")
    t0 = time.perf_counter()
    try:
        while not stop.is_set():
            if (args.run_seconds > 0
                    and time.perf_counter() - t0 >= args.run_seconds):
                log(f"gateway: --run-seconds {args.run_seconds} "
                    "elapsed; draining")
                break
            stop.wait(0.2)
    finally:
        snap = None
        try:
            gw.close()                     # graceful drain inside
            snap = gw.snapshot()
        except Exception as e:
            log(f"gateway: close failed ({type(e).__name__}: {e})")
        summary = {
            "metric": "gateway_summary",
            "url": gw.url,
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        if flight_dir:
            summary["flight_dir"] = flight_dir
        if snap is not None:
            summary.update({
                "gateway": snap["gateway"],
                "server": {k: snap["server"][k] for k in
                           ("submitted", "completed", "evicted",
                            "segments", "refills", "member_steps",
                            "resizes") if k in snap["server"]},
                "occupancy_mean": snap["occupancy_mean"],
                "warm_compiles": snap["warm_compiles"],
                "steady_recompiles": (snap["compile_count"]
                                      - snap["warm_compiles"]),
            })
            if "autoscale" in snap:
                summary["autoscale"] = snap["autoscale"]
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
