"""Load-harness CLI: generate arrival traces, replay them, report SLOs.

Two modes::

    # 1. Generate a deterministic heavy-tailed trace (no jax needed):
    python scripts/loadgen.py generate trace.jsonl --n 200 --seed 7 \
        [--mean-gap 0.5] [--tail-alpha 1.5] [--lengths 24,41,17,56]

    # 2. Replay it against a gateway and print ONE JSON SLO line:
    python scripts/loadgen.py run trace.jsonl --url http://127.0.0.1:8080 \
        [--time-scale 1.0] [--workers 8] [--sink loadgen.jsonl] [--dt 300]

``generate`` is byte-deterministic in (seed, parameters) — the same
command reproduces the same file, which is what makes load runs
replayable.  ``run`` measures p50/p99 request latency, goodput
(member-steps of completed work per second), and the typed-shed
accounting; per-request outcomes land in ``--sink`` as ``loadgen``
records (scripts/telemetry_report.py renders them).  Exit status 1
when the overload contract broke (an outcome that neither completed
nor shed with a typed 429/503).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from urllib.parse import urlparse

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cmd_generate(args) -> int:
    from jaxstream.loadgen.trace import generate_trace, write_trace

    kwargs = {}
    if args.lengths:
        kwargs["lengths"] = [int(x) for x in args.lengths.split(",")
                             if x.strip()]
    if args.families:
        pairs = [p.split(":") for p in args.families.split(",")
                 if p.strip()]
        kwargs["family_weights"] = {k: float(v) for k, v in pairs}
    trace = generate_trace(args.n, args.seed,
                           mean_gap_s=args.mean_gap,
                           tail_alpha=args.tail_alpha, **kwargs)
    write_trace(args.trace, trace)
    log(f"loadgen: wrote {len(trace)} requests to {args.trace} "
        f"(seed {args.seed}, mean gap {args.mean_gap}s, "
        f"tail alpha {args.tail_alpha})")
    return 0


def cmd_run(args) -> int:
    from jaxstream.loadgen.harness import run_load
    from jaxstream.loadgen.trace import read_trace

    u = urlparse(args.url)
    if not u.hostname or not u.port:
        raise SystemExit(f"--url {args.url!r} needs host and port")
    trace = read_trace(args.trace)
    summary = run_load(u.hostname, u.port, trace,
                       time_scale=args.time_scale,
                       max_workers=args.workers,
                       request_timeout=args.timeout,
                       sink=args.sink, dt=args.dt or None)
    log(f"loadgen: {summary['completed']} completed / "
        f"{summary['shed']} shed / {summary['errors']} errors of "
        f"{summary['n_requests']} in {summary['wall_s']}s; p50/p99 "
        f"{summary['latency_p50_s']}/{summary['latency_p99_s']}s, "
        f"goodput {summary['goodput_member_steps_per_sec']} "
        f"member-steps/s")
    print(json.dumps(summary))
    return 0 if summary["accounting_exact"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Generate and replay heavy-tailed request traces "
                    "against the jaxstream gateway.")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write a deterministic trace")
    g.add_argument("trace", help="output JSONL trace path")
    g.add_argument("--n", type=int, required=True,
                   help="number of requests")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--mean-gap", type=float, default=1.0,
                   help="mean inter-arrival gap (seconds)")
    g.add_argument("--tail-alpha", type=float, default=1.5,
                   help="Pareto tail shape (smaller = heavier)")
    g.add_argument("--lengths", default="",
                   help="comma-separated run-length ladder (steps)")
    g.add_argument("--families", default="",
                   help="IC weights as fam:w pairs, e.g. "
                        "'tc2:0.3,tc5:0.3,tc6:0.2,galewsky:0.2'")
    g.set_defaults(fn=cmd_generate)

    r = sub.add_parser("run", help="replay a trace against a gateway")
    r.add_argument("trace", help="JSONL trace path")
    r.add_argument("--url", required=True,
                   help="gateway base URL, e.g. http://127.0.0.1:8080")
    r.add_argument("--time-scale", type=float, default=1.0,
                   help="multiply arrival offsets (0 = one burst)")
    r.add_argument("--workers", type=int, default=8,
                   help="max in-flight client requests (closed loop)")
    r.add_argument("--timeout", type=float, default=300.0,
                   help="per-request client timeout (seconds)")
    r.add_argument("--sink", default="",
                   help="loadgen telemetry JSONL (per-request records)")
    r.add_argument("--dt", type=float, default=0.0,
                   help="seconds per stepper call, for sim-days goodput")
    r.set_defaults(fn=cmd_run)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
