"""Summarize a jaxstream telemetry JSONL file (jaxstream.obs.sink).

Usage::

    python scripts/telemetry_report.py run.jsonl [--json]

Prints, from the run's manifest + segment/guard/bench records:

  * the run identity line (config echo, devices, metric ladder);
  * a drift table — per conserved invariant: step-0 value, final
    value, final relative drift, and the max |drift| seen across all
    segment records (a conservation leak that self-cancels by the end
    still shows here);
  * a rate timeline — per segment: step range, wall seconds, steps/s,
    sim-days/sec/chip;
  * guard events (NaN / CFL breaches with their last-good step);
  * bench records, if the file came from ``bench.py --telemetry``.

``--json`` emits one machine-readable JSON object instead (the same
aggregates), for dashboards or the driver.  stdlib only — this tool
must run on a machine with no JAX installed.
"""

from __future__ import annotations

import argparse
import json
import sys


def load(path):
    records = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i + 1}: not JSON ({e})")
    if not records:
        raise SystemExit(f"{path}: empty telemetry file")
    return records


def _percentile(sorted_vals, q):
    """Linear-interpolation percentile over a SORTED list (stdlib-only
    stand-in for numpy.percentile; this tool must run without numpy)."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _request_outcomes(recs):
    """Shared aggregation of per-request records ('gateway'/'loadgen'
    kinds): completion/shed accounting + latency percentiles over the
    completed requests."""
    lat = sorted(r["latency_s"] for r in recs if r["status"] == "ok")
    shed_by = {}
    for r in recs:
        if r["status"].startswith("shed_"):
            shed_by[r["status"]] = shed_by.get(r["status"], 0) + 1
    return {
        "n_requests": len(recs),
        "completed": sum(1 for r in recs if r["status"] == "ok"),
        "evicted": sum(1 for r in recs if r["status"] == "evicted"),
        "shed": sum(shed_by.values()),
        "shed_by": shed_by,
        "errors": sum(1 for r in recs if r["status"] == "error"),
        "latency_p50_s": _percentile(lat, 50),
        "latency_p99_s": _percentile(lat, 99),
        "latency_max_s": lat[-1] if lat else None,
    }


def summarize(records):
    manifest = next((r for r in records if r.get("kind") == "manifest"), {})
    segments = [r for r in records if r.get("kind") == "segment"]
    guards = [r for r in records if r.get("kind") == "guard"]
    benches = [r for r in records if r.get("kind") == "bench"]
    serves = [r for r in records if r.get("kind") == "serve"]
    gateways = [r for r in records if r.get("kind") == "gateway"]
    loadgens = [r for r in records if r.get("kind") == "loadgen"]
    autoscales = [r for r in records if r.get("kind") == "autoscale"]

    drift = {}
    if segments:
        first, last = segments[0], segments[-1]
        for name in last.get("drift", {}):
            vals = [s["drift"][name] for s in segments
                    if name in s.get("drift", {})]
            drift[name] = {
                "initial_value": first.get("metrics", {}).get(name),
                "final_value": last.get("metrics", {}).get(name),
                "final_drift": last["drift"][name],
                "max_abs_drift": max((abs(v) for v in vals), default=0.0),
            }
    timeline = [
        {"step": s["step"], "t": s["t"], "steps": s["steps"],
         "wall_s": s["wall_s"], "steps_per_sec": s["steps_per_sec"],
         "sim_days_per_sec_per_chip": s["sim_days_per_sec_per_chip"],
         "host_wait_s": s.get("host_wait_s", 0.0)}
        for s in segments if s["steps"] > 0
    ]
    host_wait_total = sum(t["host_wait_s"] for t in timeline)
    # The continuous-batching server's occupancy/queue-depth columns
    # (jaxstream.serve 'serve' records, round 11): slot occupancy says
    # how full the member axis ran, queue depth how much traffic waited.
    serving = None
    if serves:
        occ = [s["occupancy"] for s in serves]
        util = [s.get("utilization") for s in serves
                if s.get("utilization") is not None]
        # Per-chip occupancy/utilization (round 12, multi-chip
        # placement): 'serve' records carry one value per member shard
        # ("chip" = member column; 6 devices each under panel
        # sharding).  Averaged per chip index over the records that
        # report it (bucket sizes can differ across segments).
        def _chip_means(key):
            rows = [s[key] for s in serves if s.get(key)]
            if not rows:
                return None
            width = max(len(r) for r in rows)
            means = []
            for j in range(width):
                vals = [r[j] for r in rows if j < len(r)]
                means.append(sum(vals) / len(vals))
            return means

        serving = {
            "segments": len(serves),
            "occupancy_mean": sum(occ) / len(occ),
            "occupancy_min": min(occ),
            "utilization_mean": (sum(util) / len(util)) if util else None,
            "queue_depth_max": max(s["queue_depth"] for s in serves),
            "completed": sum(s.get("completed", 0) for s in serves),
            "evicted": sum(s.get("evicted", 0) for s in serves),
            "refilled": sum(s.get("refilled", 0) for s in serves),
            "member_steps": sum(s.get("member_steps", 0)
                                for s in serves),
            "host_wait_total_s": sum(s.get("host_wait_s", 0.0)
                                     for s in serves),
            "devices": max((s.get("devices", 1) for s in serves),
                           default=1),
            "placement_modes": sorted(
                {s["placement"] for s in serves if s.get("placement")}),
            # Round 16: the capability plans the segments ran under,
            # with their proof verdicts (a 'rules_only' here means a
            # bucket ran OUTSIDE the verified matrix).
            "plans": sorted({f"{s['plan']}:{s['proof_verdict']}"
                             for s in serves
                             if s.get("plan") is not None}),
            "chip_occupancy_mean": _chip_means("chip_occupancy"),
            "chip_utilization_mean": _chip_means("chip_utilization"),
            "timeline": [
                {"bucket": s["bucket"],
                 "occupancy": s["occupancy"],
                 "utilization": s.get("utilization"),
                 "queue_depth": s["queue_depth"],
                 "wall_s": s["wall_s"],
                 "host_wait_s": s.get("host_wait_s", 0.0),
                 "devices": s.get("devices", 1),
                 "completed": s.get("completed", 0),
                 "evicted": s.get("evicted", 0),
                 "refilled": s.get("refilled", 0)}
                for s in serves],
        }
    # Network front-door columns (round 14): per-request outcomes seen
    # by the gateway ('gateway' records) and by the load harness's
    # clients ('loadgen' records), plus the applied autoscale resizes.
    gateway = _request_outcomes(gateways) if gateways else None
    loadgen = _request_outcomes(loadgens) if loadgens else None
    autoscale = None
    if autoscales:
        autoscale = {
            "resizes": len(autoscales),
            "events": [{"from_bucket": a["from_bucket"],
                        "to_bucket": a["to_bucket"],
                        "queue_depth": a["queue_depth"],
                        "occupancy": a["occupancy"],
                        "reason": a["reason"]} for a in autoscales],
        }
    return {"manifest": manifest, "drift": drift, "timeline": timeline,
            "host_wait_total_s": host_wait_total,
            "guards": guards, "bench": benches, "serving": serving,
            "gateway": gateway, "loadgen": loadgen,
            "autoscale": autoscale,
            "n_segments": len(segments)}


def print_report(s):
    m = s["manifest"]
    cfg, dev = m.get("config", {}), m.get("devices", {})
    print("run:", json.dumps(cfg))
    print(f"devices: {dev.get('count', '?')}x {dev.get('platform', '?')} "
          f"(process {dev.get('process_index', 0)}/"
          f"{dev.get('process_count', 1)}), jax "
          f"{m.get('jax_version', '?')}")
    print(f"metrics: {', '.join(m.get('metric_names', []))} "
          f"(every {m.get('interval', '?')} steps; guards="
          f"{m.get('guards', 'off')})")

    if s["drift"]:
        print("\ndrift vs step 0:")
        print(f"  {'metric':<12} {'initial':>14} {'final':>14} "
              f"{'final drift':>12} {'max |drift|':>12}")
        for name, d in s["drift"].items():
            ini = d["initial_value"]
            fin = d["final_value"]
            print(f"  {name:<12} "
                  f"{ini if ini is None else format(ini, '>14.7g')} "
                  f"{fin if fin is None else format(fin, '>14.7g')} "
                  f"{d['final_drift']:>12.3e} {d['max_abs_drift']:>12.3e}")

    if s["timeline"]:
        print("\nrate timeline:")
        print(f"  {'step':>8} {'t (s)':>12} {'steps':>7} {'wall s':>9} "
              f"{'steps/s':>10} {'sd/s/chip':>10} {'host wait s':>11}")
        for seg in s["timeline"]:
            print(f"  {seg['step']:>8} {seg['t']:>12.0f} "
                  f"{seg['steps']:>7} {seg['wall_s']:>9.3f} "
                  f"{seg['steps_per_sec']:>10.2f} "
                  f"{seg['sim_days_per_sec_per_chip']:>10.4f} "
                  f"{seg['host_wait_s']:>11.4f}")
        print(f"  host I/O wait blocking dispatch, total: "
              f"{s['host_wait_total_s']:.4f}s "
              f"(io.async_pipeline moves this off the critical path)")

    if s.get("serving"):
        sv = s["serving"]
        print("\nserving (continuous-batching server):")
        print(f"  {'bucket':>6} {'chips':>5} {'occupancy':>9} "
              f"{'util':>6} {'queue':>5} {'wall s':>9} "
              f"{'host wait':>9} {'done':>5} {'evict':>5} "
              f"{'refill':>6}")
        for seg in sv["timeline"]:
            util = seg["utilization"]
            print(f"  {seg['bucket']:>6} {seg['devices']:>5} "
                  f"{seg['occupancy']:>9.3f} "
                  f"{util if util is None else format(util, '>6.3f')} "
                  f"{seg['queue_depth']:>5} {seg['wall_s']:>9.4f} "
                  f"{seg['host_wait_s']:>9.4f} "
                  f"{seg['completed']:>5} {seg['evicted']:>5} "
                  f"{seg['refilled']:>6}")
        print(f"  {sv['segments']} segments: occupancy mean "
              f"{sv['occupancy_mean']:.3f} (min {sv['occupancy_min']:.3f}"
              f"), max queue depth {sv['queue_depth_max']}, "
              f"{sv['completed']} completed / {sv['evicted']} evicted / "
              f"{sv['refilled']} refilled, {sv['member_steps']} "
              f"member-steps, host wait {sv['host_wait_total_s']:.4f}s")
        if sv.get("chip_occupancy_mean"):
            modes = ",".join(sv["placement_modes"]) or "?"
            occ_c = " ".join(f"{v:.3f}"
                             for v in sv["chip_occupancy_mean"])
            line = (f"  per-chip (placement {modes}, "
                    f"{sv['devices']} devices): occupancy [{occ_c}]")
            if sv.get("chip_utilization_mean"):
                util_c = " ".join(f"{v:.3f}"
                                  for v in sv["chip_utilization_mean"])
                line += f" utilization [{util_c}]"
            print(line)

    for name in ("gateway", "loadgen"):
        sec = s.get(name)
        if not sec:
            continue
        p50, p99 = sec["latency_p50_s"], sec["latency_p99_s"]
        print(f"\n{name} requests:")
        print(f"  {sec['n_requests']} requests: {sec['completed']} "
              f"completed / {sec['evicted']} evicted / {sec['shed']} "
              f"shed / {sec['errors']} errors")
        if p50 is not None:
            print(f"  latency p50 {p50:.4f}s  p99 {p99:.4f}s  "
                  f"max {sec['latency_max_s']:.4f}s")
        for kind, count in sorted(sec["shed_by"].items()):
            print(f"  shed {kind.replace('shed_', '')}: {count}")

    if s.get("autoscale"):
        az = s["autoscale"]
        print(f"\nautoscale events ({az['resizes']}):")
        for ev in az["events"]:
            print(f"  bucket {ev['from_bucket']} -> {ev['to_bucket']} "
                  f"(queue {ev['queue_depth']}, occupancy "
                  f"{ev['occupancy']:.3f}, {ev['reason']})")

    if s["guards"]:
        print("\nguard events:")
        for g in s["guards"]:
            who = (f", member {g['member']}" if g.get("member") is not None
                   else "")
            if g.get("chip") is not None:
                who += f" on chip {g['chip']}"
            print(f"  step {g['step']}: {g['event']} (value {g['value']:g},"
                  f" policy {g['policy']}{who}, last good step "
                  f"{g['last_good_step']})")
    else:
        print("\nguard events: none")

    for b in s["bench"]:
        extra = {k: v for k, v in b.items()
                 if k not in ("kind", "metric", "value", "unit")}
        print(f"bench: {b['metric']} = {b['value']} {b['unit']}"
              + (f"  {json.dumps(extra)}" if extra else ""))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a jaxstream telemetry JSONL file.")
    ap.add_argument("path", help="telemetry JSONL file (obs.sink format)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    args = ap.parse_args(argv)
    s = summarize(load(args.path))
    if args.json:
        print(json.dumps(s))
    else:
        print_report(s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
