"""Summarize a jaxstream telemetry JSONL file (jaxstream.obs.sink).

Usage::

    python scripts/telemetry_report.py run.jsonl [more.jsonl ...]
        [--json] [--trace REQUEST_ID]

Prints, from the run's manifest + segment/guard/bench records:

  * the run identity line (config echo, devices, metric ladder);
  * a drift table — per conserved invariant: step-0 value, final
    value, final relative drift, and the max |drift| seen across all
    segment records (a conservation leak that self-cancels by the end
    still shows here);
  * a rate timeline — per segment: step range, wall seconds, steps/s,
    sim-days/sec/chip;
  * the serving section (occupancy/queue/host-wait timelines) — grown
    (round 17) with a p50/p99 per-phase latency decomposition table
    (queue vs compute vs host_wait vs egress ...) when the sinks carry
    ``span`` records (``serve.trace: true``);
  * guard events (NaN / CFL breaches with their last-good step);
  * the performance-observatory sections (round 19): per-chip device
    memory (last / peak watermark / capacity, from ``memory`` records
    under ``serve.memory_watch``) and the plan cost-stamp table
    (footprint bytes, compile seconds, flops-vs-analytic ratio,
    advisory headroom, from ``perf`` records under
    ``serve.cost_stamps``);
  * the warm-pool section (round 21): entry hit/miss/save counts per
    degradation rung (``warmpool`` records under ``serve.warm_pool``)
    and any advisory-headroom refusals (``headroom`` records);
  * bench records, if the file came from ``bench.py --telemetry``.

``--trace REQUEST_ID`` renders one request's span tree instead —
phase, start offset, duration, bucket/chip per leaf, plus the root's
terminal status and a leaf-sum-vs-latency check (exit 1 when the id
has no spans in the given sinks).

``--json`` emits one machine-readable JSON object instead (the same
aggregates), for dashboards or the driver.  Records whose kind this
report does not render are never silently dropped: they surface as a
loud ``unrendered kinds`` footer count (round-17 bugfix — silence hid
schema drift).  stdlib only — this tool must run on a machine with no
JAX installed.
"""

from __future__ import annotations

import argparse
import json
import sys

#: Literal copy of ``jaxstream.obs.trace.PHASE_OF`` (leaf span name ->
#: report phase bucket); this tool must run without jaxstream
#: installed, so it cannot import the source table —
#: tests/test_trace.py asserts the copies stay identical.
PHASE_OF = {
    "gateway.ingress": "ingress",
    "queue.wait": "queue",
    "serve.pack": "pack",
    "serve.segment": "compute",
    "serve.host_wait": "host_wait",
    "serve.boundary": "boundary",
    "finalize.wait": "egress",
    "result.fetch": "egress",
    "writer.flush": "egress",
    "gateway.egress": "egress",
}

#: Phase render order of the decomposition table.
PHASES = ("ingress", "queue", "pack", "compute", "host_wait",
          "boundary", "egress")

#: Record kinds summarize() renders; anything else is counted in the
#: ``unrendered_kinds`` footer instead of vanishing.
RENDERED_KINDS = frozenset({
    "manifest", "segment", "guard", "bench", "serve", "gateway",
    "loadgen", "autoscale", "span", "da", "memory", "perf",
    "flight", "crash", "resume", "warmpool", "headroom",
})


def load(path):
    records = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i + 1}: not JSON ({e})")
    if not records:
        raise SystemExit(f"{path}: empty telemetry file")
    return records


def load_many(paths):
    """Concatenate several sink files (serve + gateway + loadgen sinks
    of one deployment; a request's spans may span all of them)."""
    records = []
    for p in paths:
        records.extend(load(p))
    return records


def _percentile(sorted_vals, q):
    """Linear-interpolation percentile over a SORTED list (stdlib-only
    stand-in for numpy.percentile; this tool must run without numpy)."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _request_outcomes(recs):
    """Shared aggregation of per-request records ('gateway'/'loadgen'
    kinds): completion/shed accounting + latency percentiles over the
    completed requests."""
    lat = sorted(r["latency_s"] for r in recs if r["status"] == "ok")
    shed_by = {}
    for r in recs:
        if r["status"].startswith("shed_"):
            shed_by[r["status"]] = shed_by.get(r["status"], 0) + 1
    return {
        "n_requests": len(recs),
        "completed": sum(1 for r in recs if r["status"] == "ok"),
        "evicted": sum(1 for r in recs if r["status"] == "evicted"),
        "shed": sum(shed_by.values()),
        "shed_by": shed_by,
        "errors": sum(1 for r in recs if r["status"] == "error"),
        "latency_p50_s": _percentile(lat, 50),
        "latency_p99_s": _percentile(lat, 99),
        "latency_max_s": lat[-1] if lat else None,
    }


def spans_by_request(records):
    """Group ``span`` records by request id (stdlib mirror of
    ``jaxstream.obs.trace.spans_by_request``)."""
    out = {}
    for rec in records:
        if rec.get("kind") == "span":
            out.setdefault(rec["id"], []).append(rec)
    return out


def phase_decomposition(spans_by_id):
    """Per-phase latency decomposition over completed span trees.

    For every SERVED request — one root span plus at least one leaf:
    sum its leaf durations into the PHASE_OF buckets, then report
    p50/p99 seconds per phase plus each phase's mean share of
    end-to-end latency — the table that answers 'is the fleet
    queue-bound or compute-bound' at a glance.  Shed requests (a
    root-only terminal span, duration ~0) are excluded: counting them
    would dilute the percentiles toward zero exactly when the fleet
    is overloaded.
    """
    per_phase = {ph: [] for ph in PHASES}
    shares = {ph: [] for ph in PHASES}
    lat = []
    n = 0
    for spans in spans_by_id.values():
        root = next((s for s in spans if s.get("parent_id") is None),
                    None)
        if root is None or root.get("duration_s") is None:
            continue
        sums = {}
        for s in spans:
            if s.get("parent_id") is None:
                continue
            ph = PHASE_OF.get(s.get("name"))
            if ph is not None:
                sums[ph] = sums.get(ph, 0.0) + s.get("duration_s", 0.0)
        if not sums:
            continue                    # shed terminal span: no leaves
        total = root["duration_s"]
        n += 1
        lat.append(total)
        for ph in PHASES:
            if ph in sums:
                per_phase[ph].append(sums[ph])
                shares[ph].append(sums[ph] / total if total else 0.0)
    if not n:
        return None
    lat.sort()
    table = {}
    for ph in PHASES:
        vals = sorted(per_phase[ph])
        if not vals:
            continue
        table[ph] = {
            "n": len(vals),
            "p50_s": _percentile(vals, 50),
            "p99_s": _percentile(vals, 99),
            "mean_share": sum(shares[ph]) / len(shares[ph]),
        }
    return {"requests": n, "latency_p50_s": _percentile(lat, 50),
            "latency_p99_s": _percentile(lat, 99), "phases": table}


def span_tree_report(records, request_id):
    """One request's span tree (the ``--trace`` payload), keyed by
    request id or trace id; None when the sinks carry no such spans."""
    spans = [r for r in records if r.get("kind") == "span"
             and (r.get("id") == request_id
                  or r.get("trace_id") == request_id)]
    if not spans:
        return None
    root = next((s for s in spans if s.get("parent_id") is None), None)
    leaves = sorted((s for s in spans
                     if s.get("parent_id") is not None),
                    key=lambda s: (s.get("start_s", 0.0),
                                   s.get("seq", 0)))
    leaf_sum = sum(s.get("duration_s", 0.0) for s in leaves)
    out = {
        "id": spans[0].get("id"),
        "trace_id": spans[0].get("trace_id"),
        "status": root.get("status") if root else None,
        "latency_s": root.get("duration_s") if root else None,
        "n_roots": sum(1 for s in spans
                       if s.get("parent_id") is None),
        "leaf_sum_s": round(leaf_sum, 6),
        "leaves": [{
            "name": s.get("name"),
            "phase": PHASE_OF.get(s.get("name"), "?"),
            "start_s": s.get("start_s"),
            "duration_s": s.get("duration_s"),
            "bucket": s.get("bucket"),
            "plan": s.get("plan"),
            "chip": s.get("chip"),
            "steps": s.get("steps"),
        } for s in leaves],
    }
    return out


def summarize(records):
    manifest = next((r for r in records if r.get("kind") == "manifest"), {})
    segments = [r for r in records if r.get("kind") == "segment"]
    guards = [r for r in records if r.get("kind") == "guard"]
    benches = [r for r in records if r.get("kind") == "bench"]
    serves = [r for r in records if r.get("kind") == "serve"]
    gateways = [r for r in records if r.get("kind") == "gateway"]
    loadgens = [r for r in records if r.get("kind") == "loadgen"]
    autoscales = [r for r in records if r.get("kind") == "autoscale"]
    das = [r for r in records if r.get("kind") == "da"]
    memories = [r for r in records if r.get("kind") == "memory"]
    perfs = [r for r in records if r.get("kind") == "perf"]
    unrendered = {}
    for r in records:
        kind = r.get("kind")
        if kind not in RENDERED_KINDS:
            key = str(kind)
            unrendered[key] = unrendered.get(key, 0) + 1

    drift = {}
    if segments:
        first, last = segments[0], segments[-1]
        for name in last.get("drift", {}):
            vals = [s["drift"][name] for s in segments
                    if name in s.get("drift", {})]
            drift[name] = {
                "initial_value": first.get("metrics", {}).get(name),
                "final_value": last.get("metrics", {}).get(name),
                "final_drift": last["drift"][name],
                "max_abs_drift": max((abs(v) for v in vals), default=0.0),
            }
    timeline = [
        {"step": s["step"], "t": s["t"], "steps": s["steps"],
         "wall_s": s["wall_s"], "steps_per_sec": s["steps_per_sec"],
         "sim_days_per_sec_per_chip": s["sim_days_per_sec_per_chip"],
         "host_wait_s": s.get("host_wait_s", 0.0)}
        for s in segments if s["steps"] > 0
    ]
    host_wait_total = sum(t["host_wait_s"] for t in timeline)
    # The continuous-batching server's occupancy/queue-depth columns
    # (jaxstream.serve 'serve' records, round 11): slot occupancy says
    # how full the member axis ran, queue depth how much traffic waited.
    serving = None
    if serves:
        occ = [s["occupancy"] for s in serves]
        util = [s.get("utilization") for s in serves
                if s.get("utilization") is not None]
        # Per-chip occupancy/utilization (round 12, multi-chip
        # placement): 'serve' records carry one value per member shard
        # ("chip" = member column; 6 devices each under panel
        # sharding).  Averaged per chip index over the records that
        # report it (bucket sizes can differ across segments).
        def _chip_means(key):
            rows = [s[key] for s in serves if s.get(key)]
            if not rows:
                return None
            width = max(len(r) for r in rows)
            means = []
            for j in range(width):
                vals = [r[j] for r in rows if j < len(r)]
                means.append(sum(vals) / len(vals))
            return means

        serving = {
            "segments": len(serves),
            "occupancy_mean": sum(occ) / len(occ),
            "occupancy_min": min(occ),
            "utilization_mean": (sum(util) / len(util)) if util else None,
            "queue_depth_max": max(s["queue_depth"] for s in serves),
            "completed": sum(s.get("completed", 0) for s in serves),
            "evicted": sum(s.get("evicted", 0) for s in serves),
            "refilled": sum(s.get("refilled", 0) for s in serves),
            "member_steps": sum(s.get("member_steps", 0)
                                for s in serves),
            "host_wait_total_s": sum(s.get("host_wait_s", 0.0)
                                     for s in serves),
            "devices": max((s.get("devices", 1) for s in serves),
                           default=1),
            "placement_modes": sorted(
                {s["placement"] for s in serves if s.get("placement")}),
            # Round 16: the capability plans the segments ran under,
            # with their proof verdicts (a 'rules_only' here means a
            # bucket ran OUTSIDE the verified matrix).
            "plans": sorted({f"{s['plan']}:{s['proof_verdict']}"
                             for s in serves
                             if s.get("plan") is not None}),
            "chip_occupancy_mean": _chip_means("chip_occupancy"),
            "chip_utilization_mean": _chip_means("chip_utilization"),
            "timeline": [
                {"bucket": s["bucket"],
                 "occupancy": s["occupancy"],
                 "utilization": s.get("utilization"),
                 "queue_depth": s["queue_depth"],
                 "wall_s": s["wall_s"],
                 "host_wait_s": s.get("host_wait_s", 0.0),
                 "devices": s.get("devices", 1),
                 "completed": s.get("completed", 0),
                 "evicted": s.get("evicted", 0),
                 "refilled": s.get("refilled", 0)}
                for s in serves],
        }
    # Network front-door columns (round 14): per-request outcomes seen
    # by the gateway ('gateway' records) and by the load harness's
    # clients ('loadgen' records), plus the applied autoscale resizes.
    gateway = _request_outcomes(gateways) if gateways else None
    loadgen = _request_outcomes(loadgens) if loadgens else None
    autoscale = None
    if autoscales:
        autoscale = {
            "resizes": len(autoscales),
            "events": [{"from_bucket": a["from_bucket"],
                        "to_bucket": a["to_bucket"],
                        "queue_depth": a["queue_depth"],
                        "occupancy": a["occupancy"],
                        "reason": a["reason"]} for a in autoscales],
        }
    # Round 18: the EnKF assimilation cycle ('da' records, jaxstream.
    # da) — prior/posterior spread + ensemble-mean RMSE per cycle;
    # the spread trend is the filter-health signal at a glance.
    assimilation = None
    if das:
        last = das[-1]
        assimilation = {
            "cycles": len(das),
            "mode": last.get("mode", "?"),
            "nobs": last.get("nobs"),
            "final_rmse": last["rmse"],
            "final_rmse_post": last["rmse_post"],
            "final_spread": last["spread_post"],
            "rmse_trend": [d["rmse"] for d in das],
            "spread_trend": [d["spread"] for d in das],
            "timeline": [
                {"cycle": d["cycle"], "t": d["t"],
                 "spread": d["spread"], "rmse": d["rmse"],
                 "spread_post": d["spread_post"],
                 "rmse_post": d["rmse_post"],
                 "innovation_rms": d["innovation_rms"]}
                for d in das],
        }
    # Round 19: the performance observatory's columns.  'memory'
    # records (serve.memory_watch) aggregate into per-chip last /
    # peak-watermark / capacity; 'perf' records (serve.cost_stamps)
    # are one row per compiled plan — footprint bytes, compile
    # seconds, the flops-vs-analytic ratio and the advisory headroom.
    memory = None
    polls = [m for m in memories if m.get("bytes_in_use")]
    if memories:
        unavailable = next((m["unavailable"] for m in memories
                            if m.get("unavailable")), None)
        memory = {"polls": len(polls), "unavailable": unavailable}
        if polls:
            width = max(len(m["bytes_in_use"]) for m in polls)
            last = polls[-1]

            def col(key, j):
                vals = [m[key][j] for m in polls if j < len(m[key])]
                return vals

            memory.update({
                "devices": width,
                "last_bytes_in_use": last["bytes_in_use"],
                "peak_bytes": [max(col("peak_bytes", j) or [0])
                               for j in range(width)],
                "limit_bytes": last["limit_bytes"],
            })
    perf = None
    if perfs:
        perf = {"stamps": [
            {"plan": p.get("plan"), "bucket": p.get("bucket"),
             "group": p.get("group"),
             "compile_seconds": p.get("compile_seconds"),
             "footprint_bytes": (p.get("memory") or {}).get(
                 "total_bytes"),
             "memory_unavailable": (p.get("memory") or {}).get(
                 "unavailable"),
             "flops_ratio": p.get("flops_ratio"),
             "in_band": p.get("in_band"),
             "headroom_frac": p.get("headroom_frac")}
            for p in perfs]}
    # Round 17: the per-phase latency decomposition over span trees
    # (serve.trace).  Grown into the serving section when one exists
    # (the spans came from the serve sink); standalone otherwise (a
    # gateway-only sink still decomposes its ingress/egress spans).
    spans = phase_decomposition(spans_by_request(records))
    if serving is not None and spans is not None:
        serving["phase_latency"] = spans
    # Round 21: the warm-pool compile-tax columns.  'warmpool' records
    # count entry hits/misses/saves per degradation rung (aot ->
    # stablehlo -> compile_cache -> cold); 'headroom' records are the
    # advisory-headroom refusals (a resize or speculative build the
    # server declined because the stamped per-chip headroom breached
    # serve.min_headroom_frac).
    warmpools = [r for r in records if r.get("kind") == "warmpool"]
    headrooms = [r for r in records if r.get("kind") == "headroom"]
    warm_pool = None
    if warmpools or headrooms:
        by_event, rungs = {}, {}
        for w in warmpools:
            ev = str(w.get("event", "?"))
            by_event[ev] = by_event.get(ev, 0) + 1
            if ev in ("hit", "save"):
                rg = str(w.get("rung", "?"))
                rungs[rg] = rungs.get(rg, 0) + 1
        warm_pool = {
            "events": dict(sorted(by_event.items())),
            "rungs": dict(sorted(rungs.items())),
            "refusals": [{"action": h.get("action"),
                          "bucket": h.get("bucket"),
                          "headroom_frac": h.get("headroom_frac"),
                          "min_headroom_frac":
                              h.get("min_headroom_frac")}
                         for h in headrooms],
        }
    # Round 20: crash forensics.  'crash' records point at the flight-
    # recorder bundle a dying run committed, 'flight' records carry
    # the ring-dump accounting, 'resume' records stamp the lineage a
    # restarted run descends from — together they answer "did this
    # deployment die, where is the black box, and who restarted from
    # it" without leaving the report.
    forensics = None
    crashes = [r for r in records if r.get("kind") == "crash"]
    flights = [r for r in records if r.get("kind") == "flight"]
    resumes = [r for r in records if r.get("kind") == "resume"]
    if crashes or flights or resumes:
        forensics = {
            "crashes": [{"bundle": c.get("bundle"),
                         "path": c.get("path"),
                         "reason": c.get("reason")} for c in crashes],
            "dumps": [{"events": f.get("events"),
                       "threads": f.get("threads"),
                       "dropped": f.get("dropped")} for f in flights],
            "resumes": [{"bundle": r.get("bundle"),
                         "checkpoint_step": r.get("checkpoint_step"),
                         "step": r.get("step")} for r in resumes],
        }
    return {"manifest": manifest, "drift": drift, "timeline": timeline,
            "host_wait_total_s": host_wait_total,
            "guards": guards, "bench": benches, "serving": serving,
            "gateway": gateway, "loadgen": loadgen,
            "autoscale": autoscale, "spans": spans,
            "assimilation": assimilation,
            "memory": memory, "perf": perf, "forensics": forensics,
            "warm_pool": warm_pool,
            "unrendered_kinds": dict(sorted(unrendered.items())),
            "n_segments": len(segments)}


def print_report(s):
    m = s["manifest"]
    cfg, dev = m.get("config", {}), m.get("devices", {})
    print("run:", json.dumps(cfg))
    print(f"devices: {dev.get('count', '?')}x {dev.get('platform', '?')} "
          f"(process {dev.get('process_index', 0)}/"
          f"{dev.get('process_count', 1)}), jax "
          f"{m.get('jax_version', '?')}")
    print(f"metrics: {', '.join(m.get('metric_names', []))} "
          f"(every {m.get('interval', '?')} steps; guards="
          f"{m.get('guards', 'off')})")

    if s["drift"]:
        print("\ndrift vs step 0:")
        print(f"  {'metric':<12} {'initial':>14} {'final':>14} "
              f"{'final drift':>12} {'max |drift|':>12}")
        for name, d in s["drift"].items():
            ini = d["initial_value"]
            fin = d["final_value"]
            print(f"  {name:<12} "
                  f"{ini if ini is None else format(ini, '>14.7g')} "
                  f"{fin if fin is None else format(fin, '>14.7g')} "
                  f"{d['final_drift']:>12.3e} {d['max_abs_drift']:>12.3e}")

    if s["timeline"]:
        print("\nrate timeline:")
        print(f"  {'step':>8} {'t (s)':>12} {'steps':>7} {'wall s':>9} "
              f"{'steps/s':>10} {'sd/s/chip':>10} {'host wait s':>11}")
        for seg in s["timeline"]:
            print(f"  {seg['step']:>8} {seg['t']:>12.0f} "
                  f"{seg['steps']:>7} {seg['wall_s']:>9.3f} "
                  f"{seg['steps_per_sec']:>10.2f} "
                  f"{seg['sim_days_per_sec_per_chip']:>10.4f} "
                  f"{seg['host_wait_s']:>11.4f}")
        print(f"  host I/O wait blocking dispatch, total: "
              f"{s['host_wait_total_s']:.4f}s "
              f"(io.async_pipeline moves this off the critical path)")

    if s.get("serving"):
        sv = s["serving"]
        print("\nserving (continuous-batching server):")
        print(f"  {'bucket':>6} {'chips':>5} {'occupancy':>9} "
              f"{'util':>6} {'queue':>5} {'wall s':>9} "
              f"{'host wait':>9} {'done':>5} {'evict':>5} "
              f"{'refill':>6}")
        for seg in sv["timeline"]:
            util = seg["utilization"]
            print(f"  {seg['bucket']:>6} {seg['devices']:>5} "
                  f"{seg['occupancy']:>9.3f} "
                  f"{util if util is None else format(util, '>6.3f')} "
                  f"{seg['queue_depth']:>5} {seg['wall_s']:>9.4f} "
                  f"{seg['host_wait_s']:>9.4f} "
                  f"{seg['completed']:>5} {seg['evicted']:>5} "
                  f"{seg['refilled']:>6}")
        print(f"  {sv['segments']} segments: occupancy mean "
              f"{sv['occupancy_mean']:.3f} (min {sv['occupancy_min']:.3f}"
              f"), max queue depth {sv['queue_depth_max']}, "
              f"{sv['completed']} completed / {sv['evicted']} evicted / "
              f"{sv['refilled']} refilled, {sv['member_steps']} "
              f"member-steps, host wait {sv['host_wait_total_s']:.4f}s")
        if sv.get("chip_occupancy_mean"):
            modes = ",".join(sv["placement_modes"]) or "?"
            occ_c = " ".join(f"{v:.3f}"
                             for v in sv["chip_occupancy_mean"])
            line = (f"  per-chip (placement {modes}, "
                    f"{sv['devices']} devices): occupancy [{occ_c}]")
            if sv.get("chip_utilization_mean"):
                util_c = " ".join(f"{v:.3f}"
                                  for v in sv["chip_utilization_mean"])
                line += f" utilization [{util_c}]"
            print(line)

    if s.get("spans"):
        sp = s["spans"]
        print(f"\nper-phase latency decomposition ({sp['requests']} "
              f"traced requests; p50/p99 e2e "
              f"{sp['latency_p50_s']:.4f}/{sp['latency_p99_s']:.4f}s):")
        print(f"  {'phase':<10} {'n':>5} {'p50 s':>10} {'p99 s':>10} "
              f"{'share':>7}")
        for ph in PHASES:
            row = sp["phases"].get(ph)
            if row is None:
                continue
            print(f"  {ph:<10} {row['n']:>5} {row['p50_s']:>10.4f} "
                  f"{row['p99_s']:>10.4f} {row['mean_share']:>6.1%}")

    if s.get("assimilation"):
        da = s["assimilation"]
        print(f"\nassimilation (EnKF cycle, mode {da['mode']}, "
              f"{da['nobs']} stations):")
        print(f"  {'cycle':>5} {'t (s)':>10} {'spread':>10} "
              f"{'rmse':>10} {'spread+':>10} {'rmse+':>10} "
              f"{'innov rms':>10}")
        for c in da["timeline"]:
            print(f"  {c['cycle']:>5} {c['t']:>10.0f} "
                  f"{c['spread']:>10.4f} {c['rmse']:>10.4f} "
                  f"{c['spread_post']:>10.4f} {c['rmse_post']:>10.4f} "
                  f"{c['innovation_rms']:>10.4f}")
        print(f"  {da['cycles']} cycles: final rmse "
              f"{da['final_rmse']:.4f} (post-analysis "
              f"{da['final_rmse_post']:.4f}), final spread "
              f"{da['final_spread']:.4f}")

    if s.get("memory"):
        mem = s["memory"]
        print(f"\ndevice memory ({mem['polls']} polls):")
        if mem.get("unavailable"):
            print(f"  unavailable: {mem['unavailable']}")
        if mem.get("last_bytes_in_use"):
            print(f"  {'chip':>4} {'in use':>14} {'peak':>14} "
                  f"{'limit':>14} {'peak/limit':>10}")
            for j, used in enumerate(mem["last_bytes_in_use"]):
                peak = mem["peak_bytes"][j]
                limit = (mem["limit_bytes"][j]
                         if j < len(mem["limit_bytes"]) else 0)
                frac = (f"{peak / limit:>10.1%}" if limit
                        else f"{'?':>10}")
                print(f"  {j:>4} {used:>14} {peak:>14} "
                      f"{limit:>14} {frac}")

    if s.get("perf"):
        print("\nplan cost stamps:")
        print(f"  {'plan':<28} {'bucket':>6} {'compile s':>10} "
              f"{'footprint':>12} {'fl ratio':>8} {'band':>5} "
              f"{'headroom':>9}")
        for p in s["perf"]["stamps"]:
            foot = (p["footprint_bytes"]
                    if p["footprint_bytes"] is not None
                    else (p.get("memory_unavailable") or "-")[:12])
            band = ("ok" if p["in_band"]
                    else "OUT" if p["in_band"] is False else "-")
            hr = (f"{p['headroom_frac']:>9.3f}"
                  if p.get("headroom_frac") is not None
                  else f"{'-':>9}")
            cs = (f"{p['compile_seconds']:>10.3f}"
                  if p.get("compile_seconds") is not None
                  else f"{'-':>10}")
            print(f"  {str(p['plan']):<28.28} "
                  f"{'' if p['bucket'] is None else p['bucket']:>6} "
                  f"{cs} {foot:>12} "
                  f"{'-' if p['flops_ratio'] is None else format(p['flops_ratio'], '>8.3f')} "
                  f"{band:>5} {hr}")

    if s.get("warm_pool"):
        wp = s["warm_pool"]
        evs = " ".join(f"{k}={v}" for k, v in wp["events"].items())
        rungs = " ".join(f"{k}={v}" for k, v in wp["rungs"].items())
        print(f"\nwarm pool (compile tax):")
        print(f"  events: {evs or 'none'}")
        if rungs:
            print(f"  rungs (hits+saves): {rungs}")
        for r in wp["refusals"]:
            print(f"  headroom refusal: {r['action']} bucket "
                  f"{r['bucket']} (stamped headroom "
                  f"{r['headroom_frac']} < min "
                  f"{r['min_headroom_frac']})")

    for name in ("gateway", "loadgen"):
        sec = s.get(name)
        if not sec:
            continue
        p50, p99 = sec["latency_p50_s"], sec["latency_p99_s"]
        print(f"\n{name} requests:")
        print(f"  {sec['n_requests']} requests: {sec['completed']} "
              f"completed / {sec['evicted']} evicted / {sec['shed']} "
              f"shed / {sec['errors']} errors")
        if p50 is not None:
            print(f"  latency p50 {p50:.4f}s  p99 {p99:.4f}s  "
                  f"max {sec['latency_max_s']:.4f}s")
        for kind, count in sorted(sec["shed_by"].items()):
            print(f"  shed {kind.replace('shed_', '')}: {count}")

    if s.get("autoscale"):
        az = s["autoscale"]
        print(f"\nautoscale events ({az['resizes']}):")
        for ev in az["events"]:
            print(f"  bucket {ev['from_bucket']} -> {ev['to_bucket']} "
                  f"(queue {ev['queue_depth']}, occupancy "
                  f"{ev['occupancy']:.3f}, {ev['reason']})")

    if s.get("forensics"):
        fo = s["forensics"]
        print("\ncrash forensics:")
        for c in fo["crashes"]:
            print(f"  crash: {c['reason']} -> bundle {c['bundle']} "
                  f"at {c['path']}")
        for f in fo["dumps"]:
            print(f"  flight ring dumped: {f['events']} events, "
                  f"{f['threads']} thread(s), {f['dropped']} dropped")
        for r in fo["resumes"]:
            print(f"  resume: step {r['step']} from checkpoint step "
                  f"{r['checkpoint_step']} (lineage bundle "
                  f"{r['bundle']})")
        print("  postmortem: python scripts/postmortem.py <bundle> "
              "--sink <this file>")

    if s["guards"]:
        print("\nguard events:")
        for g in s["guards"]:
            who = (f", member {g['member']}" if g.get("member") is not None
                   else "")
            if g.get("chip") is not None:
                who += f" on chip {g['chip']}"
            print(f"  step {g['step']}: {g['event']} (value {g['value']:g},"
                  f" policy {g['policy']}{who}, last good step "
                  f"{g['last_good_step']})")
    else:
        print("\nguard events: none")

    for b in s["bench"]:
        extra = {k: v for k, v in b.items()
                 if k not in ("kind", "metric", "value", "unit")}
        print(f"bench: {b['metric']} = {b['value']} {b['unit']}"
              + (f"  {json.dumps(extra)}" if extra else ""))

    if s.get("unrendered_kinds"):
        parts = ", ".join(f"{k} x{v}"
                          for k, v in s["unrendered_kinds"].items())
        print(f"\n!! unrendered kinds (this report does not know them "
              f"— schema drift?): {parts}")


def print_trace(tree):
    print(f"request {tree['id']} (trace {tree['trace_id']}): "
          f"status {tree['status']}, latency "
          f"{tree['latency_s'] if tree['latency_s'] is None else format(tree['latency_s'], '.6f')}s, "
          f"{len(tree['leaves'])} leaf spans, leaf sum "
          f"{tree['leaf_sum_s']:.6f}s")
    if tree["n_roots"] != 1:
        print(f"!! {tree['n_roots']} root spans (expected exactly 1)")
    print(f"  {'phase':<10} {'span':<16} {'start s':>10} {'dur s':>10} "
          f"{'bucket':>6} {'chip':>4}  attrs")
    for lf in tree["leaves"]:
        attrs = " ".join(
            f"{k}={lf[k]}" for k in ("plan", "steps")
            if lf.get(k) is not None)
        print(f"  {lf['phase']:<10} {lf['name']:<16} "
              f"{lf['start_s']:>10.6f} {lf['duration_s']:>10.6f} "
              f"{'' if lf['bucket'] is None else lf['bucket']:>6} "
              f"{'' if lf['chip'] is None else lf['chip']:>4}  {attrs}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize jaxstream telemetry JSONL file(s).")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="telemetry JSONL file(s) (obs.sink format); "
                         "pass a deployment's serve + gateway + "
                         "loadgen sinks together")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    ap.add_argument("--trace", metavar="REQUEST_ID", default=None,
                    help="render one request's span tree (by request "
                         "id or trace id) instead of the summary")
    args = ap.parse_args(argv)
    records = load_many(args.paths)
    if args.trace is not None:
        tree = span_tree_report(records, args.trace)
        if tree is None:
            print(f"no span records for request {args.trace!r} in "
                  f"{', '.join(args.paths)} (was the deployment "
                  f"running with serve.trace: true?)")
            return 1
        if args.json:
            print(json.dumps(tree))
        else:
            print_trace(tree)
        return 0
    s = summarize(records)
    if args.json:
        print(json.dumps(s))
    else:
        print_report(s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
