"""Per-generation perf model: measure v5e, predict v5p (VERDICT r1 #1d).

Decomposes the measured C384 TC5 step into three components with
different hardware-scaling laws, each pinned by a measurement on THIS
chip (no hand-waving):

  C  VPU-compute time   — scales with the peak-compute ratio
  E  exposed-DMA time   — scales with the HBM-bandwidth ratio, estimated
                          from the measured bf16-carry delta: halving a
                          known byte count moves the step by E's
                          sensitivity to bytes
  F  fixed/other time   — stage machinery that tracked neither knob plus
                          the XLA-level glue (router, copies) read from a
                          jax.profiler device trace; scaling uncertain,
                          so the prediction brackets it (unscaled =
                          conservative, compute-scaled = optimistic)

Run on the v5e:  python scripts/perf_model.py [--measure]
Without --measure it uses the constants recorded below (measured
2026-07-30, jax 0.9.0, C384 TC5 f32 compact stepper; see DESIGN.md).
"""

import sys

# ---- measured inputs (v5e, C384 TC5, dispatch-overhead-free) -----------
STEP_F32_US = 302.0       # 3 312 steps/s, scripts/perf_probe.py
STEP_BF16_US = 282.0      # 3 547 steps/s, h-anomaly + u bf16 carry
STAGE_KERNEL_US = 263.0   # sum of the 3 Pallas stage kernels per step,
                          # jax.profiler device trace (body.9/10/11:
                          # 0.527 s over 2 000 steps)
GLUE_US = 35.0            # device while-loop total 298 us minus kernels:
                          # router matmul/gather/rev/copy XLA ops


def _analytic_constants(n=384):
    """The step cost from the ONE analytic model (round-19 dedupe:
    ``jaxstream.obs.perf.analytic_cost`` — this file previously
    carried hand-expanded ``137 * 6 * 384 * 384`` constants, and its
    bf16 line still billed ALL 27 field passes as halved
    (``27 -> 13.5``), the stale pre-round-10 accounting: only the 24
    carry passes halve, the orography re-read stays f32.  The
    corrected saved-bytes figure shrinks the inferred exposed-DMA
    sensitivity accordingly; the decomposition below now states the
    model it actually uses.  Imported lazily (sys.path dance
    included) so the no-argument prediction mode stays runnable —
    though no longer jax-free — and fails with a clear import error
    rather than at the top of the file."""
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from jaxstream.obs.perf import analytic_cost

    f32 = analytic_cost(n)
    c16 = analytic_cost(n, carry_bytes=2)
    return (f32["flops"],             # analytic count (+-15%)
            f32["bytes"],             # 27 field passes at 4 B
            f32["bytes"] - c16["bytes"])  # bytes a 16-bit carry saves

# ---- hardware ratios (v5p / v5e) ---------------------------------------
V5E_HBM_GBPS = 819.0
COMPUTE_RATIO = 459.0 / 197.0   # peak TFLOPs ratio ~ VPU clockxcores
HBM_RATIO = 2765.0 / V5E_HBM_GBPS
V5P_TARGET_DAYS = 1000.0 / 256.0  # north star normalized per chip
DT = 60.0        # rounds 1-3 step (comparability)
DT_CFL = 75.0    # the round-4 CFL-matched default (bench.py bench_tc5)


def model(step_f32_us=None, step_bf16_us=None):
    FLOPS_PER_STEP, BYTES_F32_PER_STEP, BYTES_SAVED_BY_BF16 = \
        _analytic_constants()
    step_f32_us = STEP_F32_US if step_f32_us is None else step_f32_us
    step_bf16_us = STEP_BF16_US if step_bf16_us is None else step_bf16_us
    # E: exposed-DMA sensitivity from the bf16 experiment.  Saving
    # BYTES_SAVED_BY_BF16 (the 24 carry passes at 2 B instead of 4 —
    # corrected round-10/19 accounting; the orography re-read stays
    # f32) bought (step_f32_us - step_bf16_us), so the exposed
    # fraction of raw DMA time is measured, not assumed.
    d_bytes = BYTES_SAVED_BY_BF16
    raw_us_per_byte = 1.0 / (V5E_HBM_GBPS * 1e3)   # us/byte at v5e HBM BW
    saved_us = step_f32_us - step_bf16_us
    exposure = saved_us / (d_bytes * raw_us_per_byte)
    E = BYTES_F32_PER_STEP * raw_us_per_byte * exposure

    # C: VPU time of the RHS at the measured ~2.0-2.3 TFLOP/s sustained
    # (DESIGN.md stage bisection).  Use the analytic flop count over the
    # sustained rate band; take the midpoint.
    C_lo = FLOPS_PER_STEP / 2.3e6   # us
    C_hi = FLOPS_PER_STEP / 2.0e6
    C = 0.5 * (C_lo + C_hi)

    F = step_f32_us - C - E
    print(f"v5e decomposition (per step): C={C:.0f}us (VPU), "
          f"E={E:.0f}us (exposed DMA, exposure={exposure:.2f}), "
          f"F={F:.0f}us (fixed/glue; profiler: {STAGE_KERNEL_US:.0f}us "
          f"kernels + {GLUE_US:.0f}us XLA glue)")

    for fname, fscale in (("conservative (F unscaled)", 1.0),
                          ("optimistic (F compute-scaled)", COMPUTE_RATIO)):
        step_v5p = C / COMPUTE_RATIO + E / HBM_RATIO + F / fscale
        rate = 1e6 / step_v5p
        days = rate * DT / 86400.0
        days75 = rate * DT_CFL / 86400.0
        print(f"v5p prediction [{fname}]: {step_v5p:.0f}us/step -> "
              f"{rate:.0f} steps/s -> {days75:.2f} sim-days/s/chip at "
              f"dt=75 ({days:.2f} at dt=60) "
              f"({days75 / V5P_TARGET_DAYS:.2f}x the per-chip north "
              f"star; 256-chip ensemble aggregate "
              f"{days75 * 256:.0f} sim-days/s)")


def measure():
    """Re-measure the constants live (v5e with the tunneled chip)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc5
    from jaxstream.stepping import integrate
    from jaxstream.utils.profiling import steady_state_rate

    n, dt = 384, DT
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model_ = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                   omega=EARTH_OMEGA, b_ext=b_ext,
                                   backend="pallas")
    out = {}
    for name, carry, off in (("f32", None, 0.0),
                             ("bf16", (jnp.bfloat16,) * 2, 4846.0)):
        st = model_.initial_state(h_ext, v_ext)
        step = model_.make_fused_step(dt, carry_dtype=carry, h_offset=off)
        y = model_.encode_carry(model_.compact_state(st), carry, off)
        run = jax.jit(lambda y, k: integrate(step, y, 0.0, k, dt),
                      donate_argnums=0)
        y, _ = run(y, 10)
        jax.block_until_ready(y["h"])
        rate, y = steady_state_rate(lambda y, k: run(y, k)[0], y)
        out[name] = 1e6 / rate
        print(f"measured {name}: {rate:.0f} steps/s ({out[name]:.0f} us)")
    print(f"-> measured STEP_F32_US={out['f32']:.0f}, "
          f"STEP_BF16_US={out['bf16']:.0f}")
    return out


if __name__ == "__main__":
    if "--measure" in sys.argv:
        m = measure()
        model(m["f32"], m["bf16"])
    else:
        model()
