"""Per-stage cube-edge exchange latency microbenchmark (CLI).

Thin command-line front end over :mod:`jaxstream.utils.comm_probe` —
see that module for the methodology (chained-dependency ppermute ping
per schedule stage, full production exchange, overlap on/off
steady-state step rates).

Usage::

    python scripts/comm_probe.py [n] [--iters K] [--steps K]
                                 [--temporal-block K] [--members B]
                                 [--strip-dtype f32|bf16]
                                 [--serve BUCKETS [--serve-devices D]]
                                 [--json]

``--temporal-block K`` adds the deep-halo blocked stepper's rate and
the static exchanges/step + redundant-compute accounting
(:func:`jaxstream.utils.comm_probe.temporal_block_plan`).
``--members B`` adds the batched ensemble stepper's member-steps/s and
the batched-exchange payload/ppermute accounting
(:func:`jaxstream.utils.comm_probe.batched_exchange_plan`).
``--strip-dtype bf16`` (round 10) re-bills the PLAN accounting at
2 bytes per exchanged strip element — the wire-byte savings a 16-bit
strips policy banks (``jaxstream.ops.pallas.precision``).  Measured
latencies still ship f32 strips (the sharded steppers run f32
numerics); the plans tag the savings explicitly.

Every analytic plan (temporal-block, batched-exchange, serve
placement) now carries a ``schedule_fingerprint`` (round 13): the
canonical digest of the 4-stage race-free schedule the accounting
assumes, printed as a ``sched=...`` tag on the report lines and
emitted in ``--json``.  ``scripts/analyze.py`` recomputes the same
digest from the traced steppers' actual ``ppermute`` perms and fails
if they ever diverge — the plans are an enforced contract, not
parallel bookkeeping.

``--serve BUCKETS`` (round 12) prints the serving placement-plan
report instead of the latency probes: for each placement mode
(member-parallel / panel-sharded), per batch-size bucket, the
resolved device split and the exchange bytes per step it would put on
the wire (``jaxstream.utils.comm_probe.serve_placement_plan``).  Pure
arithmetic — runs in milliseconds with no devices.  ``--serve-devices
D`` sizes the pool (default 8); ``[n]`` and ``--strip-dtype`` apply.

Device selection: uses the DEFAULT platform's devices when at least 6
exist (a real slice measures real ICI); otherwise falls back to 6
virtual CPU devices (structural dispatch-level numbers only — the
report tags every line with the platform so the two are never
confused).  For the CPU fallback the host-device-count flag must be in
place before JAX's CPU backend initializes; running this file as
__main__ sets it before importing jax.
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    args = [a for a in sys.argv[1:]]
    n_arg = int(args[0]) if args and args[0].isdigit() else 0
    iters = 100
    steps = 30
    temporal_block = 0
    members = 0
    strip_dtype = "f32"
    serve_buckets = None
    serve_devices = 8
    as_json = "--json" in args
    for i, a in enumerate(args):
        if a == "--serve":
            if i + 1 >= len(args) or args[i + 1].startswith("--"):
                print("usage: comm_probe.py ... --serve BUCKETS "
                      "(e.g. --serve 1,4,16)", file=sys.stderr)
                raise SystemExit(2)
            try:
                serve_buckets = [int(b) for b in args[i + 1].split(",")
                                 if b.strip()]
            except ValueError:
                print(f"--serve {args[i + 1]!r}: buckets must be a "
                      f"comma-separated list of ints", file=sys.stderr)
                raise SystemExit(2)
            continue
        if a == "--serve-devices":
            if i + 1 >= len(args) or not args[i + 1].isdigit():
                print("usage: comm_probe.py ... --serve-devices D",
                      file=sys.stderr)
                raise SystemExit(2)
            serve_devices = int(args[i + 1])
            continue
        if a in ("--iters", "--steps", "--temporal-block", "--members"):
            if i + 1 >= len(args) or not args[i + 1].isdigit():
                print(f"usage: comm_probe.py [n] [--iters K] [--steps K] "
                      f"[--temporal-block K] [--members B] "
                      f"[--strip-dtype f32|bf16] [--json] "
                      f"({a} needs an integer value)",
                      file=sys.stderr)
                raise SystemExit(2)
            if a == "--iters":
                iters = int(args[i + 1])
            elif a == "--steps":
                steps = int(args[i + 1])
            elif a == "--members":
                members = int(args[i + 1])
            else:
                temporal_block = int(args[i + 1])
        elif a == "--strip-dtype":
            if i + 1 >= len(args) or args[i + 1] not in ("f32", "bf16"):
                print("usage: comm_probe.py ... --strip-dtype f32|bf16",
                      file=sys.stderr)
                raise SystemExit(2)
            strip_dtype = args[i + 1]

    from jaxstream.ops.pallas.precision import strip_dtype_bytes
    from jaxstream.utils import comm_probe

    if serve_buckets is not None:
        # Placement-plan report: pure arithmetic, no devices touched.
        n = n_arg or 96
        result = {
            "n": n,
            "serve_placement_plan": comm_probe.serve_placement_plan(
                serve_buckets, serve_devices, n,
                dtype_bytes=strip_dtype_bytes(strip_dtype)),
        }
        if as_json:
            print(json.dumps(result))
        else:
            print(comm_probe.format_report(result))
        return result

    result = comm_probe.run_default_probe(
        iters=iters, steps=steps, n=n_arg,
        temporal_block=temporal_block, members=members,
        strip_dtype_bytes=strip_dtype_bytes(strip_dtype))
    if as_json:
        print(json.dumps(result))
    else:
        print(comm_probe.format_report(result))
    return result


if __name__ == "__main__":
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    main()
