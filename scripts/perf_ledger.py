"""Cross-round performance regression ledger (round 19).

Usage::

    python scripts/perf_ledger.py [render] [BENCH_r*.json ...] [--json]
    python scripts/perf_ledger.py check [BENCH_r*.json ...]
        [--candidate FILE] [--max-regression PCT]
        [--max-footprint-growth PCT] [--json]
    python scripts/perf_ledger.py --fixture

``render`` (the default) parses the recorded ``BENCH_r*.json`` history
(every file under the repo root when no paths are given) into ONE
canonical machine-normalized trajectory — per section:
sim-days/sec/chip, % of roof, footprint bytes, compile seconds, and
(round 21) the ``cold_start`` warm-pool section as warm-over-cold
speedup ratios (``cold_start:warm_speedup`` /
``cold_start:resize_speedup``, higher is better) so scale-up latency
gates the way throughput does — and prints the trend table.  Hardware
classes are inferred per the normalization rules in
``jaxstream.obs.perf.parse_bench_point`` (CPU-smoke points are tagged
``reported-only`` and never gate).

``check`` gates the LAST point (or ``--candidate FILE``, a bench
stdout JSON line or a driver envelope) against the best recorded
comparable point — same section, same hardware class: a throughput
regression beyond ``--max-regression`` (default 10%) or a footprint
grown beyond ``--max-footprint-growth`` (default 50%) **exits
nonzero**.  ``bench.py`` runs the same check in-process on every run
(full + ``--smoke``) and stamps the verdict as ``perf_ledger`` in its
JSON line, asserted by ``tests/test_bench_smoke.py``.

``--fixture`` runs the check over the seeded-broken corpus (a 30%
throughput regression + a silently-grown footprint,
``jaxstream.obs.perf.broken_bench_history``) — it must exit nonzero,
or the gate has lost its teeth (tier-1 asserts this via
``tests/test_perf_obs.py`` and ``scripts/analyze.py --fixture
perf_regression``).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _take(argv, flag):
    """Pop ``flag <value>`` from argv; a flag with no value is a
    usage error (exit 2), never an IndexError traceback."""
    if flag not in argv:
        return None
    i = argv.index(flag)
    if i + 1 >= len(argv):
        print(f"perf_ledger: {flag} requires a value", file=sys.stderr)
        raise SystemExit(2)
    val = argv[i + 1]
    del argv[i:i + 2]
    return val


def _pct(argv, flag, default):
    val = _take(argv, flag)
    return default if val is None else float(val) / 100.0


def _load_points(paths):
    from jaxstream.obs import perf as obs_perf

    if not paths:
        return obs_perf.load_bench_history(REPO)
    points = []
    for p in paths:
        with open(p) as fh:
            obj = json.load(fh)
        points.append(obs_perf.parse_bench_point(
            obj, label=os.path.basename(p).rsplit(".", 1)[0]))
    return points


def main(argv=None) -> int:
    from jaxstream.obs import perf as obs_perf

    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if "--fixture" in args:
        pts = [obs_perf.parse_bench_point(o, label=f"fixture:r{o['n']}")
               for o in obs_perf.broken_bench_history()]
        res = obs_perf.check_trajectory(pts)
        print(json.dumps(res) if as_json else
              "\n".join(r["detail"] for r in res["regressions"])
              or "fixture came back CLEAN — the ledger lost its teeth")
        # Exit nonzero when the regression was CAUGHT (the CLI check
        # contract: regressions -> exit 1), which is what CI asserts.
        return 1 if not res["ok"] else 0
    max_reg = _pct(args, "--max-regression",
                   obs_perf.DEFAULT_MAX_REGRESSION)
    max_fp = _pct(args, "--max-footprint-growth",
                  obs_perf.DEFAULT_MAX_FOOTPRINT_GROWTH)
    candidate = _take(args, "--candidate")
    cmd = "render"
    if args and args[0] in ("render", "check"):
        cmd = args.pop(0)
    points = _load_points(args)
    if candidate is not None:
        with open(candidate) as fh:
            text = fh.read().strip()
        obj = json.loads(text.splitlines()[-1])
        points.append(obs_perf.parse_bench_point(
            obj, label=os.path.basename(candidate)))
    if not points:
        print("perf_ledger: no BENCH_r*.json history found",
              file=sys.stderr)
        return 2
    if cmd == "render":
        if as_json:
            print(json.dumps({"points": points}))
        else:
            print(obs_perf.render_trajectory(points))
        return 0
    res = obs_perf.check_trajectory(points, max_regression=max_reg,
                                    max_footprint_growth=max_fp)
    if as_json:
        print(json.dumps(res))
    else:
        mode = "ENFORCED" if res["enforced"] else "reported-only"
        print(f"perf_ledger check [{mode}]: candidate "
              f"{res['candidate']} ({res['hardware_class']}) vs "
              f"{res['points'] - 1} recorded point(s), "
              f"{res['compared_sections']} section(s) compared")
        for r in res["regressions"]:
            print(f"  REGRESSION {r['detail']}")
        for r in res["advisories"]:
            print(f"  advisory   {r['detail']}")
        if res["ok"] and not res["advisories"]:
            if res["compared_sections"]:
                print("  clean — no section regressed beyond the band")
            else:
                print("  VACUOUS pass — no comparable recorded point "
                      "shares a section with this candidate (nothing "
                      "was gated)")
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
