"""Capability-plan explainer (CLI front end for jaxstream.plan).

Usage::

    python scripts/plan.py explain <config.yaml | YAML string>
    python scripts/plan.py explain <config> --serve
    python scripts/plan.py --enumerate [n] [--json]

``explain`` resolves a config through ``plan_for`` and prints the
normalized :class:`~jaxstream.plan.plan.CapabilityPlan` — tier, every
composition knob, the capability key, the canonical schedule
fingerprint (explicit-exchange tiers), the declared runtime parity
budget, and the proof stamp the built stepper will carry, plus the
analytic half of its round-19 cost stamp (flops/bytes/AI per step —
the measured footprint/compile fields land where a compile happens).
An illegal
composition prints the rule pointers and exits 2 — the same messages,
from the same table, the factories raise at build time, shown here
*statically* before any trace.  ``--serve`` resolves the config as an
``EnsembleServer`` deployment instead of a Simulation run.

``--enumerate`` walks the rule table and lists the complete legal plan
space at the given resolution (default 12) with per-tier counts and
the rule-table version — the exact space ``jaxstream.analysis``
verifies and the bench ``contract_check`` stamp records.

``--json`` prints one JSON line instead of the human table.  Pure
config arithmetic: no devices, no jax tracing.
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _explain(source: str, serving: bool, as_json: bool) -> int:
    from jaxstream.obs.perf import build_cost
    from jaxstream.plan import PlanError, build_proof, plan_for

    try:
        plan = plan_for(source, serving=serving)
    except PlanError as e:
        if as_json:
            print(json.dumps({
                "ok": False,
                "violations": [{"rule": v.rule, "pointer": v.pointer}
                               for v in e.violations]}))
        else:
            print("ILLEGAL plan:" if e.violations else str(e))
            for v in e.violations:
                print(f"  [{v.rule}] {v.pointer}")
        return 2
    stamp = build_proof(plan)
    # Round 19: the analytic half of the cost stamp the built stepper
    # will carry — pure arithmetic, printed statically like the rest
    # of explain (the measured half lands where a compile happens:
    # serve warmup under serve.cost_stamps, the bench perf section).
    cost = build_cost(plan, plan_key=stamp.plan_key)
    if as_json:
        print(json.dumps({"ok": True, "plan": plan.describe(),
                          "proof": stamp.to_json(),
                          "cost": cost.to_json()}))
        return 0
    d = plan.describe()
    print(f"plan: {d.pop('key')}   (rules v{d.pop('rules_version')})")
    fp = d.pop("schedule_fingerprint")
    parity = d.pop("parity")
    for k in sorted(d):
        print(f"  {k:16s} {d[k]}")
    print(f"  schedule         "
          f"{fp or '- (no explicit exchange collectives)'}")
    ref = parity["reference"] or "- (this IS the reference plan)"
    budget = ("bitwise" if parity["budget"] == 0.0
              else f"<= {parity['budget']:g} rel")
    print(f"  parity           {budget} vs {ref}")
    print(f"proof: {stamp}")
    ana = cost.analytic
    if ana is not None:
        print(f"cost:  analytic {ana['flops'] / 1e9:.4f} GFLOP/step, "
              f"{ana['bytes'] / 1e6:.3f} MB/step, "
              f"AI {ana['ai']:.3f} flops/byte ({ana['basis']})")
    else:
        print("cost:  analytic - (no covariant stencil model for "
              "this tier)")
    print("cost:  footprint/compile-seconds land when the plan "
          "compiles (serve.cost_stamps, bench perf section)")
    return 0


def _enumerate(n: int, as_json: bool) -> int:
    from collections import Counter

    from jaxstream.plan import RULES_VERSION, enumerate_plans

    plans = enumerate_plans(n=n)
    if as_json:
        print(json.dumps({
            "n": n, "rules_version": RULES_VERSION,
            "size": len(plans),
            "keys": [p.key() for p in plans]}))
        return 0
    counts = Counter(("serve" if p.serving else p.tier)
                     for p in plans)
    print(f"legal capability-plan space at n={n} "
          f"(rules v{RULES_VERSION}): {len(plans)} plans")
    for tier, c in sorted(counts.items()):
        print(f"  {tier:12s} {c}")
    for p in plans:
        print(f"  - {p.key()}")
    return 0


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    serving = "--serve" in args
    args = [a for a in args if a not in ("--json", "--serve")]
    if args and args[0] == "--enumerate":
        n = int(args[1]) if len(args) > 1 and args[1].isdigit() else 12
        return _enumerate(n, as_json)
    if len(args) == 2 and args[0] == "explain":
        return _explain(args[1], serving, as_json)
    print(__doc__.split("Usage::", 1)[1].split("``explain``")[0],
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
