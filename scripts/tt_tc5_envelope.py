"""TC5 C96 stability envelope of the factored sphere SWE.

Measures, per (rank, kappa) configuration, how far the factored TC5
integration runs before going non-finite (up to --days), and the final
h-error against the dense twin run with the SAME kappa (so the error
reported is rank-truncation error, not the dissipation difference).
Feeds the rank-vs-horizon table in DESIGN.md ("stability envelope").

Methodology matches the round-2 envelope measurement: f64, CPU backend,
dt=300 s, finiteness checked every `check` steps on the unfactored h.

    python scripts/tt_tc5_envelope.py [--days 5] [--ranks 8,16,24,32]
        [--kappas 0,1e5,3e5,1e6] [--n 96] [--rounding aca|svd]

Prints one JSON line per configuration (and a final dense reference
line per kappa).  Round-4 result (DESIGN.md envelope table): under
--rounding aca every configuration NaNs within 0.17-0.5 days; under
--rounding svd rank 8+ integrates the full 5 days at truncation-level
error — the blowup was ACA's excess over optimal truncation.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--days", type=float, default=5.0)
    ap.add_argument("--dt", type=float, default=300.0)
    ap.add_argument("--n", type=int, default=96)
    ap.add_argument("--ranks", default="8,16,24,32")
    ap.add_argument("--kappas", default="0,1e5,3e5,1e6")
    ap.add_argument("--check", type=int, default=48,
                    help="steps between finiteness checks (48 = 4 h)")
    ap.add_argument("--rounding", default="aca",
                    choices=("aca", "svd", "rsvd", "host_svd"))
    ap.add_argument("--platform", default="cpu",
                    help="JAX platform to pin ('cpu' is the round-2 "
                    "methodology; 'default' leaves the process backend "
                    "alone — use with --f32 for the round-5 on-chip "
                    "stability check, since the tunneled TPU rejects "
                    "an explicit 'tpu' pin)")
    ap.add_argument("--f32", action="store_true",
                    help="run in float32 (the TPU execution dtype) "
                    "instead of the f64 CPU methodology")
    args = ap.parse_args()

    if args.platform not in ("", "default"):
        jax.config.update("jax_platforms", args.platform)
    if not args.f32:
        jax.config.update("jax_enable_x64", True)
    wdtype = jnp.float32 if args.f32 else jnp.float64

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.physics import initial_conditions as ics
    from jaxstream.tt.sphere import factor_panels, unfactor_panels
    from jaxstream.tt.sphere_swe import (covariant_from_cartesian,
                                         make_dense_sphere_swe,
                                         make_tt_sphere_swe)

    n, dt = args.n, args.dt
    nsteps = int(round(args.days * 86400.0 / dt))
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=wdtype)
    h_ext, v_ext, b_ext = ics.williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext))
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)
    area = np.asarray(grid.interior(grid.area), np.float64)

    ranks = [int(r) for r in args.ranks.split(",")]
    kappas = [float(k) for k in args.kappas.split(",")]

    # Dense references (one per kappa): the truncation-error oracle.
    dense_h = {}
    for kap in kappas:
        step = jax.jit(make_dense_sphere_swe(grid, dt, hs=b_ext,
                                             kappa=kap))
        s = (jnp.asarray(h0), jnp.asarray(ua0), jnp.asarray(ub0))
        t0 = time.time()
        for _ in range(nsteps):
            s = step(s)
        h = np.asarray(s[0], np.float64)
        fin = bool(np.isfinite(h).all())
        dense_h[kap] = h if fin else None
        print(json.dumps({
            "config": "dense", "kappa": kap, "days": args.days,
            "finite": fin,
            "h_range": [float(h.min()), float(h.max())] if fin else None,
            "wall_s": round(time.time() - t0, 1),
        }), flush=True)

    for rank in ranks:
        for kap in kappas:
            step = jax.jit(make_tt_sphere_swe(grid, dt, rank=rank,
                                              hs=b_ext, kappa=kap,
                                              rounding=args.rounding))
            p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
            t0 = time.time()
            done = 0
            horizon = None
            while done < nsteps:
                k = min(args.check, nsteps - done)
                for _ in range(k):
                    p = step(p)
                done += k
                h = np.asarray(unfactor_panels(p[0]), np.float64)
                if not np.isfinite(h).all():
                    horizon = (done - k) * dt / 86400.0
                    break
            rec = {"config": "tt", "rank": rank, "kappa": kap,
                   "rounding": args.rounding,
                   "days": args.days, "dt": dt,
                   "wall_s": round(time.time() - t0, 1)}
            if horizon is None:
                rec["finite"] = True
                rec["h_range"] = [float(h.min()), float(h.max())]
                ref = dense_h.get(kap)
                if ref is not None:
                    d = h - ref
                    rec["h_l2_vs_dense"] = float(np.sqrt(
                        np.sum(area * d**2) / np.sum(area * ref**2)))
                m0 = np.sum(area * h0)
                rec["mass_drift"] = float(abs(np.sum(area * h) - m0) / m0)
            else:
                rec["finite"] = False
                rec["horizon_days"] = round(horizon, 2)
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
