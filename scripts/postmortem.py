"""Postmortem timeline reconstructor for flight-recorder crash bundles.

Usage::

    python scripts/postmortem.py BUNDLE_DIR [--sink FILE ...] [--json]
        [--last N]
    python scripts/postmortem.py --diff DIR_A DIR_B

The forensic half of the round-20 black box (``jaxstream/obs/
flight.py``): given one committed crash bundle — and optionally the
deployment's ordinary sink files — it

* **verifies the bundle** exactly as ``flight.read_bundle`` does
  (manifest present and parseable, required keys, events file present,
  sha256 and line count match, every event line JSON) and exits ``2``
  on a torn bundle: truncation is evidence of a kill mid-commit and
  must never be silently summarized;
* **reconstructs the incident timeline** — the merged per-thread ring
  events in global sequence order, rendered with offsets relative to
  the last event (the moment of death);
* **renders what was in flight at death** — the manifest's
  open-request section: every admitted-but-unfinished request id with
  its deterministic trace id, split queued vs in-flight;
* **cross-checks the sink's trace spans** (when ``--sink`` files carry
  ``span`` records): each completed span tree's leaf sum must tile its
  root duration within the trace contract's epsilon — a root/leaf
  mismatch in the dying run's telemetry is itself a finding;
* summarizes the sinks' incident records (``guard``/``crash``/
  ``resume``/``autoscale``) around the bundle.

``--diff A B`` compares a RESUMED run's output directory against an
uninterrupted reference to the round-5 standard: every non-JSONL file
byte-for-byte, every ``.jsonl`` record-for-record with the wall-clock
fields masked — and with the lineage kinds (``resume``/``crash``/
``flight``) excluded, since only the resumed run legitimately carries
them.  Exit 1 on any difference.

Like the other operator tools this is stdlib-only: it must run on a
box with neither jaxstream nor JAX installed.  The bundle-format
constants and the trace epsilons are literal copies of the source
(``jaxstream.obs.flight`` / ``jaxstream.obs.trace``); tests assert the
copies stay identical.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

#: Literal copy of ``jaxstream.obs.flight.BUNDLE_MANIFEST``.
BUNDLE_MANIFEST = "bundle.json"

#: Literal copies of ``jaxstream.obs.trace`` span-contract epsilons.
EPSILON_ABS_S = 0.05
EPSILON_FRAC = 0.05

#: Wall-clock fields masked by ``--diff`` (superset of the async-
#: pipeline parity test's volatile list: span/latency stamps differ
#: run-to-run too).
VOLATILE_FIELDS = ("wall_s", "steps_per_sec", "sim_days_per_sec_per_chip",
                   "host_wait_s", "created_unix", "latency_s",
                   "start_s", "duration_s", "queue_depth")

#: Record kinds only a resumed/crashed run carries — excluded from
#: ``--diff`` so lineage stamps don't fail the parity they document.
LINEAGE_KINDS = frozenset({"resume", "crash", "flight"})

#: Exit code for a torn bundle (distinct from a plain mismatch).
EXIT_TORN = 2


class Torn(SystemExit):
    """Torn-bundle rejection: SystemExit with the forensic message."""

    def __init__(self, message: str):
        print(f"TORN BUNDLE: {message}", file=sys.stderr)
        super().__init__(EXIT_TORN)


# ------------------------------------------------------------ verification
def read_bundle(bundle_dir):
    """Stdlib mirror of ``jaxstream.obs.flight.read_bundle`` — same
    checks, same order; raises :class:`Torn` (exit 2) instead of
    TornBundleError."""
    mpath = os.path.join(bundle_dir, BUNDLE_MANIFEST)
    if not os.path.exists(mpath):
        raise Torn(f"{bundle_dir}: no {BUNDLE_MANIFEST} — the bundle "
                   "was never committed (killed before the os.replace "
                   "commit point?)")
    try:
        with open(mpath, "rb") as fh:
            manifest = json.loads(fh.read().decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise Torn(f"{mpath}: manifest is not JSON ({e})")
    for key in ("bundle_id", "events_file", "n_events", "events_sha256"):
        if key not in manifest:
            raise Torn(f"{mpath}: manifest is missing {key!r}")
    epath = os.path.join(bundle_dir, manifest["events_file"])
    if not os.path.exists(epath):
        raise Torn(f"{bundle_dir}: manifest names "
                   f"{manifest['events_file']} but the file is gone")
    with open(epath, "rb") as fh:
        payload = fh.read()
    digest = hashlib.sha256(payload).hexdigest()
    if digest != manifest["events_sha256"]:
        raise Torn(f"{epath}: sha256 {digest[:12]}… does not match the "
                   f"manifest's {manifest['events_sha256'][:12]}… — "
                   "the events file is torn or tampered")
    lines = [ln for ln in payload.decode("utf-8").split("\n") if ln]
    if len(lines) != manifest["n_events"]:
        raise Torn(f"{epath}: {len(lines)} events on disk, manifest "
                   f"promises {manifest['n_events']}")
    events = []
    for i, ln in enumerate(lines):
        try:
            events.append(json.loads(ln))
        except ValueError as e:
            raise Torn(f"{epath}:{i + 1}: event is not JSON ({e})")
    return manifest, events


def load_sinks(paths):
    records = []
    for path in paths:
        with open(path) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise SystemExit(f"{path}:{i + 1}: not JSON ({e})")
    return records


# ------------------------------------------------------------- cross-check
def span_check(records):
    """Root-vs-leaf-sum verification over every completed span tree:
    ``{checked, ok, mismatches: [...]}`` or None when the sinks carry
    no spans.  The contract is the trace module's: |root - leaf_sum|
    <= max(EPSILON_ABS_S, EPSILON_FRAC * root)."""
    by_id = {}
    for rec in records:
        if rec.get("kind") == "span":
            by_id.setdefault(rec["id"], []).append(rec)
    if not by_id:
        return None
    checked = ok = 0
    mismatches = []
    for rid, spans in sorted(by_id.items()):
        root = next((s for s in spans if s.get("parent_id") is None),
                    None)
        leaves = [s for s in spans if s.get("parent_id") is not None]
        if root is None or not leaves:
            continue                 # shed terminal / incomplete tree
        checked += 1
        root_s = float(root.get("duration_s", 0.0))
        leaf_sum = sum(float(s.get("duration_s", 0.0)) for s in leaves)
        tol = max(EPSILON_ABS_S, EPSILON_FRAC * root_s)
        if abs(root_s - leaf_sum) <= tol:
            ok += 1
        else:
            mismatches.append({
                "id": rid, "trace_id": root.get("trace_id"),
                "root_s": round(root_s, 6),
                "leaf_sum_s": round(leaf_sum, 6),
                "tolerance_s": round(tol, 6),
            })
    return {"checked": checked, "ok": ok, "mismatches": mismatches}


# --------------------------------------------------------------- timeline
def build_report(manifest, events, sink_records, last=40):
    t_death = manifest.get("wall_time") or (
        events[-1]["t"] if events else 0.0)
    open_reqs = manifest.get("open_requests") or {}
    incidents = [r for r in sink_records
                 if r.get("kind") in ("guard", "crash", "resume",
                                      "autoscale")]
    by_type = {}
    for e in events:
        by_type[e.get("type", "?")] = by_type.get(e.get("type", "?"),
                                                  0) + 1
    return {
        "bundle_id": manifest["bundle_id"],
        "reason": manifest.get("reason"),
        "wall_time": manifest.get("wall_time"),
        "commit": manifest.get("commit"),
        "n_events": manifest["n_events"],
        "dropped_events": manifest.get("dropped_events", 0),
        "threads": manifest.get("threads") or {},
        "events_by_type": by_type,
        "checkpoint": manifest.get("checkpoint"),
        "device_memory": manifest.get("device_memory"),
        "open_requests": open_reqs,
        "n_open": (len(open_reqs.get("queued", []))
                   + len(open_reqs.get("in_flight", []))),
        "timeline": [
            dict(e, dt_s=round(e["t"] - t_death, 3))
            for e in events[-last:]],
        "incidents": incidents,
        "span_check": span_check(sink_records),
    }


def print_report(r):
    when = (time.strftime("%Y-%m-%d %H:%M:%S",
                          time.localtime(r["wall_time"]))
            if r.get("wall_time") else "?")
    print(f"crash bundle {r['bundle_id']}  (commit {r['commit']}, "
          f"{when})")
    print(f"  reason: {r['reason']}")
    print(f"  ring: {r['n_events']} events across "
          f"{len(r['threads'])} thread(s)"
          + (f", {r['dropped_events']} DROPPED (ring wrapped)"
             if r["dropped_events"] else ""))
    for thread, n in sorted(r["threads"].items()):
        print(f"    {thread}: {n} appended")
    if r["events_by_type"]:
        tops = sorted(r["events_by_type"].items(),
                      key=lambda kv: -kv[1])
        print("  event mix: " + ", ".join(
            f"{t} x{n}" for t, n in tops))
    ck = r.get("checkpoint")
    print(f"  last checkpoint: step {ck['step']} at {ck['path']}"
          if ck else "  last checkpoint: none")
    mem = r.get("device_memory")
    if mem:
        print(f"  device memory: {mem}")

    print(f"\nin flight at death ({r['n_open']} open request(s)):")
    oreq = r["open_requests"]
    for section in ("in_flight", "queued"):
        rows = oreq.get(section, [])
        print(f"  {section} ({len(rows)}):")
        for row in rows:
            print(f"    {row['id']:<24} trace {row['trace_id']}")
    if not r["n_open"]:
        print("  (none — the process died idle)")

    print(f"\ntimeline (last {len(r['timeline'])} events, "
          "dt relative to death):")
    for e in r["timeline"]:
        extra = {k: v for k, v in e.items()
                 if k not in ("seq", "t", "thread", "type", "dt_s")}
        detail = (" " + " ".join(f"{k}={v}"
                                 for k, v in sorted(extra.items()))
                  if extra else "")
        print(f"  {e['dt_s']:>9.3f}s  [{e['thread']}] "
              f"{e['type']}{detail}")

    if r["incidents"]:
        print(f"\nsink incident records ({len(r['incidents'])}):")
        for rec in r["incidents"]:
            kind = rec.get("kind")
            if kind == "guard":
                print(f"  guard: {rec.get('event')} at step "
                      f"{rec.get('step')} (value {rec.get('value')})")
            elif kind == "crash":
                print(f"  crash: bundle {rec.get('bundle')} "
                      f"({rec.get('reason')}) at {rec.get('path')}")
            elif kind == "resume":
                print(f"  resume: from bundle {rec.get('bundle')} at "
                      f"checkpoint step {rec.get('checkpoint_step')}")
            else:
                print(f"  autoscale: {rec.get('from_bucket')} -> "
                      f"{rec.get('to_bucket')} "
                      f"({rec.get('reason')})")

    sc = r.get("span_check")
    if sc is not None:
        print(f"\ntrace cross-check: {sc['ok']}/{sc['checked']} span "
              "trees tile their root latency")
        for m in sc["mismatches"]:
            print(f"  !! {m['id']}: root {m['root_s']}s vs leaf sum "
                  f"{m['leaf_sum_s']}s (tol {m['tolerance_s']}s)")


# ------------------------------------------------------------------- diff
def _masked_records(path):
    out = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i + 1}: not JSON ({e})")
            if rec.get("kind") in LINEAGE_KINDS:
                continue
            out.append({k: v for k, v in rec.items()
                        if k not in VOLATILE_FIELDS})
    return out


def _walk(root):
    out = {}
    for dirpath, _, names in os.walk(root):
        for name in names:
            p = os.path.join(dirpath, name)
            out[os.path.relpath(p, root)] = p
    return out


def diff_runs(dir_a, dir_b) -> int:
    """Round-5-standard comparison of two run output directories;
    prints each difference, returns the number found."""
    fa, fb = _walk(dir_a), _walk(dir_b)
    problems = 0
    for rel in sorted(set(fa) | set(fb)):
        if rel not in fa or rel not in fb:
            print(f"DIFF {rel}: only in "
                  f"{dir_a if rel in fa else dir_b} (missing from "
                  f"{dir_b if rel in fa else dir_a})")
            problems += 1
            continue
        if rel.endswith(".jsonl"):
            ra, rb = _masked_records(fa[rel]), _masked_records(fb[rel])
            if ra != rb:
                n = min(len(ra), len(rb))
                at = next((i for i in range(n) if ra[i] != rb[i]), n)
                print(f"DIFF {rel}: record {at} differs "
                      f"({len(ra)} vs {len(rb)} records after "
                      "masking)")
                problems += 1
        else:
            with open(fa[rel], "rb") as f1, open(fb[rel], "rb") as f2:
                if f1.read() != f2.read():
                    print(f"DIFF {rel}: bytes differ")
                    problems += 1
    if not problems:
        print(f"OK: {len(fa)} files equal to the round-5 standard "
              "(bytes; JSONL modulo wall-clock fields and lineage "
              "records)")
    return problems


# ------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Reconstruct an incident timeline from a flight-"
                    "recorder crash bundle (+ sink files), or --diff "
                    "two run directories.")
    ap.add_argument("bundle", nargs="?", default="",
                    help="crash-bundle directory (or a flight dir — "
                         "the newest committed bundle inside is used)")
    ap.add_argument("--sink", action="append", default=[],
                    help="telemetry JSONL to merge into the postmortem "
                         "(repeatable: serve + gateway + simulation "
                         "sinks)")
    ap.add_argument("--last", type=int, default=40,
                    help="timeline events to render (default 40)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--diff", nargs=2, metavar=("DIR_A", "DIR_B"),
                    help="compare a resumed run's output directory "
                         "against an uninterrupted reference")
    args = ap.parse_args(argv)

    if args.diff:
        return 1 if diff_runs(*args.diff) else 0
    if not args.bundle:
        ap.error("BUNDLE_DIR required (or --diff DIR_A DIR_B)")

    bdir = args.bundle
    if not os.path.exists(os.path.join(bdir, BUNDLE_MANIFEST)):
        # Maybe a flight dir full of bundles: take the newest committed
        # one — matching flight.latest_bundle's wall_time ordering.
        best, best_key = None, None
        if os.path.isdir(bdir):
            for name in sorted(os.listdir(bdir)):
                mpath = os.path.join(bdir, name, BUNDLE_MANIFEST)
                if not os.path.isfile(mpath):
                    continue
                try:
                    with open(mpath) as fh:
                        m = json.load(fh)
                except (OSError, ValueError):
                    continue
                key = (m.get("wall_time", 0.0), m.get("commit", 0))
                if best_key is None or key > best_key:
                    best, best_key = os.path.join(bdir, name), key
        if best is None:
            raise Torn(f"{bdir}: no committed bundle found")
        bdir = best

    manifest, events = read_bundle(bdir)
    sink_records = load_sinks(args.sink)
    report = build_report(manifest, events, sink_records,
                          last=args.last)
    if args.json:
        print(json.dumps(report))
    else:
        print_report(report)
    sc = report["span_check"]
    return 1 if (sc is not None and sc["mismatches"]) else 0


if __name__ == "__main__":
    sys.exit(main())
