"""Tier-hygiene lint for the test suite (CI/tooling satellite, round 6).

Two invariants keep the fast tier-1 gate honest, both enforced here and
run *inside* the gate via ``tests/test_check_tiers.py`` (the tier-1
command is plain pytest, so a non-slow test wrapping this lint makes
every gate run self-checking):

1. **Marker registry**: every ``pytest.mark.<name>`` used under
   ``tests/`` must be registered in ``pytest.ini``'s ``markers`` section
   (or be a pytest builtin).  An unregistered marker is how a test
   silently escapes the ``-m "not slow"`` deselection — e.g. a typo'd
   ``@pytest.mark.slwo`` runs a 40 s parity in every fast gate.

2. **Subprocess device tests are slow**: any test module that launches a
   multi-device SUBPROCESS worker (the 24-virtual-device block-mesh and
   multi-process pod parities — detected as ``subprocess`` usage next to
   a worker-script reference or a forced host-device count) must carry
   ``pytest.mark.slow``.  These are the suite's most expensive items
   (~40-90 s each); the fast tier's time budget assumes they stay out.

3. **Telemetry tests stay tier-1** (round-8 observability satellite):
   a test module importing ``jaxstream.obs`` must carry NO ``slow``
   markers.  The observability acceptance criteria (buffer parity,
   guard firing, bitwise-unchanged carry) are what the fast gate
   certifies on every run — a slow-marked telemetry parity would
   silently drop that coverage from tier-1.  Put genuinely slow
   obs-adjacent tests in a module that exercises the feature through
   ``Simulation`` without importing ``jaxstream.obs`` directly.

4. **Async-pipeline tests stay tier-1** (round-9 satellite): the same
   rule for modules importing ``jaxstream.io.async_pipeline``.  The
   async/sync bitwise file parity, the writer backpressure bound, the
   flush-on-HealthError guarantee and the thread-leak check are the
   acceptance criteria of the overlap path — they must run in every
   fast gate, not rot in the slow tier.

5. **Precision-parity tests stay tier-1** (round-10 satellite): the
   same rule for modules importing ``jaxstream.ops.pallas.precision``.
   The precision ladder's acceptance criteria — policy-off bitwise
   identity, the measured bf16-stage truncation budgets, the re-fused
   del^4 parity — are exactly what certifies that a refactor didn't
   silently change which ops run reduced; they must run in every fast
   gate (a slow-marked parity would let a bad policy ship between
   offline TPU bench runs).

6. **Serving tests stay tier-1** (round-11 satellite): the same rule
   for modules importing ``jaxstream.serve``.  The continuous-batching
   server's acceptance criteria — packing/refill determinism, the
   B=1-request bitwise parity vs a plain Simulation run, eviction-
   under-injected-NaN, queue backpressure, and the zero-steady-state-
   recompile warm-bucket claim — must run in every fast gate (the real
   throughput numbers only exist on offline TPU bench runs; the fast
   gate is what certifies the machinery between them).

7. **Multichip-serving tests ride the in-process fake devices**
   (round-12 satellite): a module importing the serving placement
   surface (``jaxstream.serve.placement``) must not launch subprocess
   workers.  Rule 6 already keeps it non-slow; the remaining way to
   lose the coverage is a rewrite onto a subprocess device worker —
   which rule 2 would then force into the slow tier, silently dropping
   the member-parallel/panel-sharded parities from every fast gate.
   The conftest's 8 virtual CPU devices exist exactly so these tests
   run in-process.

8. **Contract-checker tests stay non-slow and in-process** (round-13
   static-analysis satellite): a module importing
   ``jaxstream.analysis`` must carry NO ``slow`` markers and must not
   launch subprocesses.  The contract checks (schedule totality, the
   traced-vs-plan collective counts, the seeded-broken fixtures
   failing loudly) are the machine-checked proof of the race-free
   claim — they must run in every fast gate, on the conftest's
   in-process virtual devices; a slow-marked or subprocess rewrite
   would silently drop the proof from the gate that cites it.

9. **Gateway/loadgen tests stay non-slow and bind loopback only**
   (round-14 network-front-door satellite): a module importing
   ``jaxstream.gateway`` or ``jaxstream.loadgen`` must carry NO
   ``slow`` markers — the typed-overload contract, the loopback byte
   parity, graceful drain, trace determinism and the autoscale
   hysteresis proofs are the acceptance criteria the fast gate
   certifies between offline runs — and must never reference a
   wildcard bind address (``0.0.0.0``): gateway tests run REAL
   listening sockets, and anything but 127.0.0.1 leaks an open port
   to the network from every CI run.

10. **Config sections stay documented; plan tests stay fast +
    in-process** (round-16 capability-plan satellite).  Two halves:
    (a) every section key in ``jaxstream/config.py``'s ``_SECTIONS``
    table must appear as a top-level key inside a fenced config block
    in ``docs/USAGE.md`` — a new config section whose docs never
    landed is exactly the drift the plan layer exists to prevent
    (the rule that rejects a knob should be one ``grep`` from the doc
    that explains it); (b) a test module importing ``jaxstream.plan``
    must carry NO ``slow`` markers and must not launch subprocesses —
    the rule-table rejections, the enumerated plan space and the
    proof-stamp checks are the static proof surface of the build
    pipeline and must run in every fast gate on the in-process
    virtual devices.

11. **Tracing/dashboard tests stay non-slow, in-process, loopback
    only** (round-17 observability satellite): a module importing the
    tracing surface (``jaxstream.obs.trace`` / ``jaxstream.obs.
    registry``) or the operator dashboard (``telemetry_dashboard``)
    must carry NO ``slow`` markers, must not launch subprocesses
    (drive the dashboard/report CLIs through their importable
    ``main()``), and must never reference a wildcard bind address —
    the span-completeness proof, the metrics scrape round-trip and
    the dashboard render are the operator-view acceptance criteria
    the fast gate certifies on every run, and their gateways open
    REAL listening sockets.

12. **Assimilation tests stay non-slow and in-process** (round-18
    EnKF satellite): a module importing ``jaxstream.da`` must carry
    NO ``slow`` markers and must not launch subprocesses — the
    closed-loop forecast claim (cycled RMSE beats the free ensemble
    through the HTTP gateway), the byte-determinism of the cycle
    outputs, the seeded spread-collapse guard and the raw-array
    restart round trip are the acceptance criteria the fast gate
    certifies on every run; drive ``scripts/assimilate.py`` through
    its importable ``main()``/``run()``.

13. **Perf-observatory tests stay non-slow, in-process, and
    CPU-honest; sink kinds stay rendered** (round-19 satellite).  Two
    halves: (a) a test module importing the performance observatory
    (``jaxstream.obs.perf`` or ``perf_ledger``) must carry NO
    ``slow`` markers, must not launch subprocesses (drive
    ``scripts/perf_ledger.py`` through its importable ``main()``),
    and must not gate on accelerator-only surfaces (``skipif`` on
    tpu/gpu platforms or ``jax.devices('tpu')`` probes) — the cost-
    stamp shapes, the typed memory_analysis fallback, the
    watcher-off byte-identity and the ledger's seeded-broken fixture
    are tier-1 acceptance criteria and must run on CPU in every fast
    gate; (b) every record kind registered in
    ``jaxstream/obs/sink.py``'s ``RECORD_KINDS`` must appear in BOTH
    ``scripts/telemetry_report.py``'s and
    ``scripts/telemetry_dashboard.py``'s ``RENDERED_KINDS`` sets —
    the loud unrendered-kinds footer contract only holds if a newly
    registered kind is actually taught to both tools (a registered-
    but-unrendered kind would scream "schema drift" on every
    operator view).

14. **Flight-recorder/postmortem tests stay non-slow and in-process;
    kill tests stay slow** (round-20 black-box satellite).  Two
    halves: (a) a test module importing the flight recorder
    (``jaxstream.obs.flight``) or the postmortem reconstructor
    (``scripts/postmortem.py`` via ``import postmortem``) must carry
    NO ``slow`` markers and must not launch subprocesses — the ring
    semantics, the atomic-bundle round trip, the torn-bundle
    rejection, the sink byte-identity claim and the resume-lineage
    proof are tier-1 acceptance criteria (drive the postmortem CLI
    through its importable ``main()``); (b) any test module that
    launches subprocesses AND references a hard kill
    (``SIGKILL``/``.kill(``) must carry ``pytest.mark.slow`` — the
    SIGKILL crash-forensics capstone spawns a real serving process
    and waits on it, which is exactly the cost profile the fast
    tier's budget excludes.

15. **Warm-pool tests stay non-slow and in-process; cross-process
    cache-deserialization tests stay slow** (round-21 compile-tax
    satellite).  Two halves: (a) a test module importing the warm-pool
    surface (``jaxstream.serve.warmpool``) must carry NO ``slow``
    markers and must not launch subprocesses — the cache-key
    invalidation proofs (rules-version bump / plan / toolchain string
    MISS, never a stale hit), the torn-entry detection and the
    zero-warm-compile restart claim are tier-1 acceptance criteria;
    drive the rung probe through the pool's injectable ``probe=``
    fake, never a real child process; (b) any test module that
    launches subprocesses AND references the cross-process compile-
    cache surface (``enable_compile_cache`` / ``probe_rung`` /
    ``JAXSTREAM_COMPILE_CACHE``) must carry ``pytest.mark.slow`` —
    cross-process CPU cache deserialization is the documented
    jaxlib-0.4.37 segfault class the subprocess probe exists to
    quarantine, and a real two-process probe costs tens of seconds of
    child jax imports, which is exactly the cost profile the fast
    tier's budget excludes.

Exit status 0 = clean; 1 = violations (listed on stdout).
"""

from __future__ import annotations

import configparser
import os
import re
import sys

#: Markers pytest defines itself — always legal without registration.
BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast",
}

_MARK_RE = re.compile(r"pytest\.mark\.([A-Za-z_][A-Za-z0-9_]*)")
_WORKER_RE = re.compile(
    r"(_worker\.py|worker\.py\b|xla_force_host_platform_device_count)")
_OBS_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.obs\b|import\s+jaxstream\.obs\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*obs\b)", re.MULTILINE)
_ASYNC_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.io\.async_pipeline\b"
    r"|import\s+jaxstream\.io\.async_pipeline\b"
    r"|from\s+jaxstream\.io\s+import\s+(\w+\s*,\s*)*async_pipeline\b)",
    re.MULTILINE)
_PRECISION_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.ops\.pallas\.precision\b"
    r"|import\s+jaxstream\.ops\.pallas\.precision\b"
    r"|from\s+jaxstream\.ops\.pallas\s+import\s+(\w+\s*,\s*)*precision\b)",
    re.MULTILINE)
_SERVE_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.serve\b|import\s+jaxstream\.serve\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*serve\b)",
    re.MULTILINE)
_PLACEMENT_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.serve\.placement\b"
    r"|import\s+jaxstream\.serve\.placement\b"
    r"|from\s+jaxstream\.serve\s+import\s+[^\n]*"
    r"\b(placement|plan_placement|placement_report|BucketPlan)\b)",
    re.MULTILINE)
_ANALYSIS_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.analysis\b|import\s+jaxstream\.analysis\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*analysis\b)",
    re.MULTILINE)
_NETWORK_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.(gateway|loadgen)\b"
    r"|import\s+jaxstream\.(gateway|loadgen)\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*(gateway|loadgen)\b)",
    re.MULTILINE)
#: Anchored so real addresses merely CONTAINING the substring
#: (10.0.0.0/8, 240.0.0.0) do not trip the lint.
_WILDCARD_BIND_RE = re.compile(r"(?<![\d.])0\.0\.0\.0(?![\d.])")
_PLAN_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.plan\b|import\s+jaxstream\.plan\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*plan\b)",
    re.MULTILINE)
_TRACE_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.obs\.(trace|registry)\b"
    r"|import\s+jaxstream\.obs\.(trace|registry)\b"
    r"|from\s+jaxstream\.obs\s+import\s+[^\n]*"
    r"\b(trace|registry|RequestTrace|MetricsRegistry"
    r"|parse_exposition|span_coverage|tree_complete)\b"
    r"|import\s+telemetry_dashboard\b"
    r"|from\s+telemetry_dashboard\s+import\b)",
    re.MULTILINE)
_DA_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.da\b|import\s+jaxstream\.da\b"
    r"|from\s+jaxstream\s+import\s+(\w+\s*,\s*)*da\b)",
    re.MULTILINE)
_PERF_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.obs\.perf\b"
    r"|import\s+jaxstream\.obs\.perf\b"
    r"|from\s+jaxstream\.obs\s+import\s+[^\n]*"
    r"\b(perf|CostStamp|MemoryWatcher|measure_cost|build_cost"
    r"|check_trajectory|load_bench_history)\b"
    r"|import\s+perf_ledger\b|from\s+perf_ledger\s+import\b)",
    re.MULTILINE)
#: Accelerator-only gating a tier-1 perf-obs module must not carry:
#: a platform skipif or an explicit tpu/gpu device probe would drop
#: the observatory's acceptance criteria from every CPU CI gate.
_ACCEL_ONLY_RE = re.compile(
    r"skipif\([^)]*[\"'](tpu|gpu)[\"']"
    r"|jax\.devices\(\s*[\"'](tpu|gpu)[\"']")
_FLIGHT_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.obs\.flight\b"
    r"|import\s+jaxstream\.obs\.flight\b"
    r"|from\s+jaxstream\.obs\s+import\s+[^\n]*"
    r"\b(flight|FlightRecorder|BundleWriter|read_bundle"
    r"|latest_bundle|TornBundleError)\b"
    r"|import\s+postmortem\b|from\s+postmortem\s+import\b)",
    re.MULTILINE)
_WARMPOOL_IMPORT_RE = re.compile(
    r"^\s*(from\s+jaxstream\.serve\.warmpool\b"
    r"|import\s+jaxstream\.serve\.warmpool\b"
    r"|from\s+jaxstream\.serve\s+import\s+[^\n]*"
    r"\b(warmpool|WarmPool|WarmExecutable|HeadroomRefused"
    r"|SpeculativeCompiler)\b)",
    re.MULTILINE)
#: The cross-process compile-cache surface: a subprocess-launching
#: test referencing any of these is exercising the documented
#: jaxlib-0.4.37 cache-deserialization segfault class and must ride
#: the slow tier (rule 15b).
_CACHE_XPROC_RE = re.compile(
    r"\benable_compile_cache\b|\bprobe_rung\b"
    r"|JAXSTREAM_COMPILE_CACHE|jax\.config.*compilation_cache")
#: A hard-kill reference next to subprocess usage marks the SIGKILL
#: crash-forensics capstone (and anything shaped like it) — those
#: must ride the slow tier.
_HARD_KILL_RE = re.compile(r"\bSIGKILL\b|\.kill\(")
#: Actual subprocess USAGE (an import or an attribute call), so a
#: docstring merely mentioning the word does not trip rule 10b.
_SUBPROC_USE_RE = re.compile(
    r"^\s*(import|from)\s+subprocess\b|subprocess\.\w+",
    re.MULTILINE)
#: The _SECTIONS table in jaxstream/config.py: "name": SomeConfig,
_SECTIONS_RE = re.compile(
    r"^_SECTIONS\s*=\s*\{(.*?)\}", re.MULTILINE | re.DOTALL)
_SECTION_KEY_RE = re.compile(r"\"(\w+)\"\s*:")
_FENCE_RE = re.compile(r"^```[a-z]*\n(.*?)^```", re.MULTILINE | re.DOTALL)


def config_sections(config_py: str):
    """The ``_SECTIONS`` keys of jaxstream/config.py (regex — this
    lint must stay import-light, no jax)."""
    with open(config_py) as fh:
        m = _SECTIONS_RE.search(fh.read())
    if not m:
        return None
    return _SECTION_KEY_RE.findall(m.group(1))


def documented_sections(usage_md: str):
    """Top-level ``key:`` names inside USAGE.md's fenced blocks."""
    with open(usage_md) as fh:
        text = fh.read()
    keys = set()
    for block in _FENCE_RE.findall(text):
        for line in block.splitlines():
            m = re.match(r"^([A-Za-z_][A-Za-z0-9_]*):", line)
            if m:
                keys.add(m.group(1))
    return keys


def lint_config_docs(root: str):
    """Rule 10a: every config section has a fenced USAGE.md block."""
    config_py = os.path.join(root, "jaxstream", "config.py")
    usage_md = os.path.join(root, "docs", "USAGE.md")
    if not (os.path.exists(config_py) and os.path.exists(usage_md)):
        return                      # repo layouts without the pair
    sections = config_sections(config_py)
    if sections is None:
        yield (f"{os.path.relpath(config_py)}: could not locate the "
               f"_SECTIONS table (rule 10a parses it textually — "
               f"keep the literal dict form)")
        return
    documented = documented_sections(usage_md)
    for name in sections:
        if name not in documented:
            yield (f"docs/USAGE.md: config section {name!r} "
                   f"(_SECTIONS in jaxstream/config.py) has no fenced "
                   f"``` config block showing a top-level '{name}:' "
                   f"key — every section the plan layer can reject "
                   f"must be documented where users write it")


#: The RECORD_KINDS table in jaxstream/obs/sink.py and the
#: RENDERED_KINDS sets in the two stdlib operator tools — parsed
#: textually (this lint must stay import-light, no jax).
_RECORD_KINDS_RE = re.compile(
    r"^RECORD_KINDS[^=]*=\s*\{(.*?)^\}", re.MULTILINE | re.DOTALL)
_KIND_KEY_RE = re.compile(r"^\s{4}\"(\w+)\":", re.MULTILINE)
_RENDERED_RE = re.compile(
    r"RENDERED_KINDS\s*=\s*frozenset\(\{(.*?)\}\)", re.DOTALL)
_QUOTED_RE = re.compile(r"\"(\w+)\"")


def lint_sink_kinds(root: str):
    """Rule 13b: every registered sink kind is rendered by BOTH
    operator tools (the loud unrendered-kinds footer contract)."""
    sink_py = os.path.join(root, "jaxstream", "obs", "sink.py")
    tools = [os.path.join(root, "scripts", name) for name in
             ("telemetry_report.py", "telemetry_dashboard.py")]
    if not os.path.exists(sink_py) or not all(
            os.path.exists(t) for t in tools):
        return                      # repo layouts without the trio
    with open(sink_py) as fh:
        m = _RECORD_KINDS_RE.search(fh.read())
    if not m:
        yield (f"{os.path.relpath(sink_py)}: could not locate the "
               f"RECORD_KINDS table (rule 13b parses it textually — "
               f"keep the literal dict form)")
        return
    kinds = set(_KIND_KEY_RE.findall(m.group(1)))
    for tool in tools:
        with open(tool) as fh:
            mm = _RENDERED_RE.search(fh.read())
        rendered = set(_QUOTED_RE.findall(mm.group(1))) if mm else set()
        for kind in sorted(kinds - rendered):
            yield (f"{os.path.relpath(tool)}: sink record kind "
                   f"{kind!r} (RECORD_KINDS in jaxstream/obs/sink.py) "
                   f"is not in this tool's RENDERED_KINDS — a "
                   f"registered kind the operator view cannot render "
                   f"lands in the loud unrendered-kinds footer as "
                   f"false schema drift; teach the tool the kind (and "
                   f"render it) when registering it")


def registered_markers(pytest_ini: str) -> set:
    """Marker names registered in pytest.ini's ``markers`` option."""
    cp = configparser.ConfigParser()
    cp.read(pytest_ini)
    raw = cp.get("pytest", "markers", fallback="")
    names = set()
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        names.add(line.split(":", 1)[0].strip())
    return names


def lint_file(path: str, allowed: set):
    """Yield violation strings for one test module."""
    with open(path) as fh:
        src = fh.read()
    rel = os.path.relpath(path)
    used = set(_MARK_RE.findall(src))
    for name in sorted(used - allowed):
        yield (f"{rel}: pytest.mark.{name} is not registered in "
               f"pytest.ini (registered: {sorted(allowed - BUILTIN_MARKERS)}"
               f" + builtins) — unregistered markers escape the "
               f"-m 'not slow' tier gate")
    if "subprocess" in src and _WORKER_RE.search(src) \
            and "slow" not in used:
        yield (f"{rel}: launches a multi-device subprocess worker but "
               f"carries no pytest.mark.slow — subprocess device tests "
               f"must stay out of the fast tier")
    if _OBS_IMPORT_RE.search(src) and "slow" in used:
        yield (f"{rel}: imports jaxstream.obs but marks tests slow — "
               f"telemetry coverage must stay tier-1-clean (the fast "
               f"gate certifies the observability acceptance criteria "
               f"on every run); move the slow test to a module that "
               f"does not import jaxstream.obs")
    if _ASYNC_IMPORT_RE.search(src) and "slow" in used:
        yield (f"{rel}: imports jaxstream.io.async_pipeline but marks "
               f"tests slow — the async-pipeline acceptance criteria "
               f"(bitwise file parity, backpressure bound, "
               f"flush-on-exception, thread hygiene) must run in every "
               f"fast gate; move the slow test to a module that does "
               f"not import jaxstream.io.async_pipeline")
    if _PRECISION_IMPORT_RE.search(src) and "slow" in used:
        yield (f"{rel}: imports jaxstream.ops.pallas.precision but "
               f"marks tests slow — the precision-ladder parities "
               f"(policy-off bitwise, bf16-stage truncation budgets, "
               f"re-fused del^4) must run in every fast gate; move the "
               f"slow test to a module that does not import "
               f"jaxstream.ops.pallas.precision")
    if _SERVE_IMPORT_RE.search(src) and "slow" in used:
        yield (f"{rel}: imports jaxstream.serve but marks tests slow — "
               f"the serving acceptance criteria (packing/refill "
               f"determinism, B=1 bitwise parity vs Simulation, "
               f"eviction, backpressure, zero steady-state recompiles) "
               f"must run in every fast gate; move the slow test to a "
               f"module that does not import jaxstream.serve")
    if _PLACEMENT_IMPORT_RE.search(src) and "subprocess" in src:
        yield (f"{rel}: imports the serving placement surface "
               f"(jaxstream.serve.placement) but launches subprocesses "
               f"— multichip-serving parities must run IN-PROCESS on "
               f"the conftest's 8 virtual CPU devices (a subprocess "
               f"device worker would be forced slow by rule 2, "
               f"silently dropping member-parallel/panel-sharded "
               f"coverage from the fast gate)")
    if _NETWORK_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports jaxstream.gateway/loadgen but "
                   f"marks tests slow — the network front door's "
                   f"acceptance criteria (typed 429/503 overload, "
                   f"loopback byte parity, graceful drain, trace "
                   f"determinism, autoscale hysteresis) must run in "
                   f"every fast gate; move the slow test to a module "
                   f"that does not import jaxstream.gateway/loadgen")
        if _WILDCARD_BIND_RE.search(src):
            yield (f"{rel}: imports jaxstream.gateway/loadgen and "
                   f"references the wildcard bind address 0.0.0.0 — "
                   f"gateway tests open REAL listening sockets and "
                   f"must bind loopback (127.0.0.1) only, or every CI "
                   f"run exposes an open port to the network")
    if _PLAN_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports jaxstream.plan but marks tests "
                   f"slow — the capability-plan rejections, the "
                   f"enumerated plan space and the proof-stamp "
                   f"checks are the static proof surface of the "
                   f"build pipeline and must run in every fast gate; "
                   f"move the slow test to a module that does not "
                   f"import jaxstream.plan")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports jaxstream.plan but launches "
                   f"subprocesses — plan/pipeline tests must run "
                   f"IN-PROCESS on the conftest's virtual devices "
                   f"(a subprocess rewrite would be forced slow by "
                   f"rule 2, dropping the plan-space proof from the "
                   f"fast gate); drive scripts/plan.py through its "
                   f"importable main() instead")
    if _TRACE_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports the tracing/dashboard surface "
                   f"(jaxstream.obs.trace/registry or "
                   f"telemetry_dashboard) but marks tests slow — the "
                   f"span-completeness proof, the metrics scrape "
                   f"round-trip and the dashboard render are the "
                   f"operator-view acceptance criteria and must run "
                   f"in every fast gate; move the slow test to a "
                   f"module that does not import the tracing surface")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports the tracing/dashboard surface but "
                   f"launches subprocesses — tracing/dashboard tests "
                   f"must run IN-PROCESS (drive "
                   f"scripts/telemetry_dashboard.py and "
                   f"scripts/telemetry_report.py through their "
                   f"importable main() instead; a subprocess rewrite "
                   f"would be forced slow by rule 2, dropping the "
                   f"operator-view proof from the fast gate)")
        if _WILDCARD_BIND_RE.search(src):
            yield (f"{rel}: imports the tracing/dashboard surface and "
                   f"references the wildcard bind address 0.0.0.0 — "
                   f"traced-gateway tests open REAL listening sockets "
                   f"and must bind loopback (127.0.0.1) only")
    if _DA_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports jaxstream.da but marks tests slow "
                   f"— the assimilation acceptance criteria (the "
                   f"closed-loop gateway forecast claim, cycle byte "
                   f"determinism, the spread-collapse guard, the "
                   f"raw-array restart round trip) must run in every "
                   f"fast gate; move the slow test to a module that "
                   f"does not import jaxstream.da")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports jaxstream.da but launches "
                   f"subprocesses — assimilation tests must run "
                   f"IN-PROCESS on the conftest's virtual devices "
                   f"(drive scripts/assimilate.py through its "
                   f"importable main()/run(); a subprocess rewrite "
                   f"would be forced slow by rule 2, dropping the "
                   f"forecast-loop proof from the fast gate)")
    if _PERF_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports the performance observatory "
                   f"(jaxstream.obs.perf / perf_ledger) but marks "
                   f"tests slow — the cost-stamp shapes, the typed "
                   f"memory_analysis fallback, the watcher-off byte "
                   f"identity and the ledger's seeded-broken fixture "
                   f"must run in every fast gate; move the slow test "
                   f"to a module that does not import the observatory")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports the performance observatory but "
                   f"launches subprocesses — perf-obs tests must run "
                   f"IN-PROCESS (drive scripts/perf_ledger.py through "
                   f"its importable main(); a subprocess rewrite "
                   f"would be forced slow by rule 2, dropping the "
                   f"regression-ledger proof from the fast gate)")
        if _ACCEL_ONLY_RE.search(src):
            yield (f"{rel}: imports the performance observatory and "
                   f"gates on accelerator-only surfaces (a tpu/gpu "
                   f"skipif or device probe) — tier-1 runs on CPU, so "
                   f"an accelerator-only assert silently drops the "
                   f"observatory's acceptance criteria from every CI "
                   f"gate; use injectable stats_fn fakes and the "
                   f"typed unavailable fallbacks instead")
    if _FLIGHT_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports the flight recorder/postmortem "
                   f"surface (jaxstream.obs.flight or postmortem) but "
                   f"marks tests slow — the ring semantics, the "
                   f"atomic-bundle round trip, the torn-bundle "
                   f"rejection, the sink byte-identity claim and the "
                   f"resume-lineage proof are tier-1 acceptance "
                   f"criteria and must run in every fast gate; move "
                   f"the slow test to a module that does not import "
                   f"the flight surface")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports the flight recorder/postmortem "
                   f"surface but launches subprocesses — flight/"
                   f"postmortem tests must run IN-PROCESS (drive "
                   f"scripts/postmortem.py through its importable "
                   f"main(); the subprocess SIGKILL capstone lives in "
                   f"a module that reads the bundle JSON directly "
                   f"without importing the surface)")
    if _WARMPOOL_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports the warm-pool surface "
                   f"(jaxstream.serve.warmpool) but marks tests slow "
                   f"— the cache-key invalidation proofs, the "
                   f"torn-entry detection, the headroom refusals and "
                   f"the zero-warm-compile restart claim are tier-1 "
                   f"acceptance criteria and must run in every fast "
                   f"gate; move the slow test to a module that does "
                   f"not import the warm-pool surface")
        if _SUBPROC_USE_RE.search(src):
            yield (f"{rel}: imports the warm-pool surface but "
                   f"launches subprocesses — warm-pool tests must run "
                   f"IN-PROCESS (drive the rung probe through the "
                   f"pool's injectable probe= fake; a real "
                   f"two-process probe imports jax in a child and "
                   f"would be forced slow by rule 2, dropping the "
                   f"compile-tax proofs from the fast gate); "
                   f"cross-process cache-deserialization tests live "
                   f"in a slow-marked module that does not import "
                   f"the surface (rule 15b)")
    if _SUBPROC_USE_RE.search(src) and _CACHE_XPROC_RE.search(src) \
            and "slow" not in used:
        yield (f"{rel}: launches subprocesses and references the "
               f"cross-process compile-cache surface "
               f"(enable_compile_cache / probe_rung / "
               f"JAXSTREAM_COMPILE_CACHE) but carries no "
               f"pytest.mark.slow — cross-process CPU cache "
               f"deserialization is the documented jaxlib "
               f"segfault class the subprocess probe quarantines, "
               f"and a real child-process jax import is exactly the "
               f"cost profile the fast tier's budget excludes")
    if _SUBPROC_USE_RE.search(src) and _HARD_KILL_RE.search(src) \
            and "slow" not in used:
        yield (f"{rel}: launches subprocesses and references a hard "
               f"kill (SIGKILL/.kill() ) but carries no "
               f"pytest.mark.slow — process-kill forensics tests "
               f"spawn and wait on real serving processes, which the "
               f"fast tier's time budget excludes")
    if _ANALYSIS_IMPORT_RE.search(src):
        if "slow" in used:
            yield (f"{rel}: imports jaxstream.analysis but marks tests "
                   f"slow — the static contract checks (schedule "
                   f"totality, traced-vs-plan collective counts, the "
                   f"broken-fixture regressions) are the machine-"
                   f"checked proof of the race-free exchange claim and "
                   f"must run in every fast gate; move the slow test "
                   f"to a module that does not import "
                   f"jaxstream.analysis")
        if "subprocess" in src:
            yield (f"{rel}: imports jaxstream.analysis but launches "
                   f"subprocesses — contract checks must run "
                   f"IN-PROCESS on the conftest's virtual devices "
                   f"(a subprocess rewrite would be forced slow by "
                   f"rule 2, silently dropping the contract proof "
                   f"from the fast gate); drive scripts/analyze.py "
                   f"through its importable run()/main() instead")


def main(repo_root: str = None) -> int:
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    ini = os.path.join(root, "pytest.ini")
    if not os.path.exists(ini):
        print(f"check_tiers: no pytest.ini at {ini}")
        return 1
    allowed = registered_markers(ini) | BUILTIN_MARKERS
    tests_dir = os.path.join(root, "tests")
    violations = []
    for name in sorted(os.listdir(tests_dir)):
        if not name.endswith(".py") or not name.startswith("test_"):
            continue
        violations += list(lint_file(os.path.join(tests_dir, name),
                                     allowed))
    violations += list(lint_config_docs(root))
    violations += list(lint_sink_kinds(root))
    for v in violations:
        print("check_tiers:", v)
    if not violations:
        print(f"check_tiers: OK ({len(allowed - BUILTIN_MARKERS)} "
              f"registered markers; all subprocess device tests slow)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
