"""Shared CLI shutdown plumbing (round 20).

One signal-drain helper for ``scripts/serve.py`` and
``scripts/gateway.py``: both CLIs must react identically to SIGTERM
*and* SIGINT — dump the flight ring as an atomic crash bundle, enter
the graceful drain, and still print their one-line JSON summary on the
way out.  Before this module each CLI grew its own handler (gateway
had one, serve had none), which is exactly how the two drift apart.

The drain hook runs IN the signal handler (CPython runs handlers
between bytecodes on the main thread).  That is safe here because the
hook only flips the server's draining flag and commits the flight
bundle — small, bounded work — and it is the only way the bundle gets
written when the main thread is parked deep inside a blocking serve
loop that a mere ``stop.set()`` cannot interrupt mid-batch.
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import Callable, Optional


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def install_drain_handlers(stop: threading.Event,
                           on_drain: Optional[Callable[[str], None]] = None,
                           name: str = "serve") -> Callable:
    """Install SIGTERM + SIGINT handlers that set ``stop`` and invoke
    ``on_drain(signame)`` exactly once (later signals only re-set the
    event, so a second Ctrl-C during the drain cannot double-dump the
    bundle or re-enter the hook).  A hook failure is logged, never
    raised — a broken forensics path must not turn a clean drain into
    a crash.  Returns the installed handler (tests invoke it
    directly)."""
    fired = threading.Event()

    def on_signal(signum, frame):
        del frame
        try:
            signame = signal.Signals(signum).name
        except ValueError:
            signame = f"signal {signum}"
        log(f"{name}: received {signame}; draining")
        if on_drain is not None and not fired.is_set():
            fired.set()
            try:
                on_drain(signame)
            except Exception as e:
                log(f"{name}: drain hook failed "
                    f"({type(e).__name__}: {e})")
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    return on_signal
