"""Continuous-batching ensemble serving CLI (jaxstream.serve).

Usage::

    python scripts/serve.py config.yaml --requests trace.jsonl \
        [--output-dir DIR] [--warm flat,oro]

``config.yaml`` is the standard config surface (grid/time/physics/
model + the ``serve:`` block); ``trace.jsonl`` holds one scenario
request per line::

    {"id": "r0", "ic": "tc5", "nsteps": 288, "seed": 7,
     "amplitude": 1e-3, "outputs": ["h"]}

Requests are admitted with producer-side backpressure (submission
blocks at the queue bound while batches drain), served by packing into
the member axis, and — when ``--output-dir``/``serve.output_dir`` is
set — written as one zarr store per request through the background
writer.  Prints exactly ONE JSON summary line on stdout (request
statuses, occupancy/utilization, latency percentiles, compile counts,
host-wait totals, and — under ``serve.placement`` — the resolved
per-bucket multi-chip plan); everything else goes to stderr.  Set ``serve.sink`` for per-segment
occupancy/queue-depth telemetry readable by
``scripts/telemetry_report.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _signals  # noqa: E402 — shared CLI signal-drain helper


def load_requests(path: str):
    from jaxstream.serve import ScenarioRequest

    reqs = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                reqs.append(ScenarioRequest.from_dict(json.loads(line)))
            except (json.JSONDecodeError, TypeError, ValueError) as e:
                raise SystemExit(f"{path}:{i + 1}: bad request ({e})")
    if not reqs:
        raise SystemExit(f"{path}: no requests")
    return reqs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Serve scenario requests through the "
                    "continuous-batching ensemble server.")
    ap.add_argument("config", help="server config YAML (grid/time/"
                                   "physics/model + serve: block)")
    ap.add_argument("--requests", required=True,
                    help="JSONL request trace (one scenario per line)")
    ap.add_argument("--output-dir", default="",
                    help="override serve.output_dir (one zarr store "
                         "per request)")
    ap.add_argument("--warm", default="",
                    help="comma-separated batching groups to pre-"
                         "compile before admitting traffic "
                         "(e.g. 'flat,oro')")
    ap.add_argument("--flight-dir", default="",
                    help="flight-recorder crash-bundle directory "
                         "(default: '<serve.sink>.flight' when a sink "
                         "is configured, else off)")
    args = ap.parse_args(argv)

    import dataclasses

    import numpy as np

    from jaxstream.config import load_config
    from jaxstream.serve import EnsembleServer
    from jaxstream.serve.queue import QueueFull, ServerDraining

    cfg = load_config(args.config)
    if args.output_dir:
        cfg = dataclasses.replace(
            cfg, serve=dataclasses.replace(cfg.serve,
                                           output_dir=args.output_dir))
    # The black box: explicit --flight-dir wins; with a serve sink
    # configured the bundle lands next to it, so crash forensics are
    # on whenever telemetry is.
    flight_dir = args.flight_dir or (
        cfg.serve.sink + ".flight" if cfg.serve.sink else "")
    if flight_dir:
        cfg = dataclasses.replace(
            cfg, observability=dataclasses.replace(
                cfg.observability, flight_dir=flight_dir))
    reqs = load_requests(args.requests)
    warm = tuple(g.strip() for g in args.warm.split(",") if g.strip())

    # The server is built HERE (not via serve_requests) so the signal
    # handler can reach it: SIGTERM/SIGINT dump the flight bundle and
    # begin the graceful drain, and the summary still prints.
    stop = threading.Event()
    server = EnsembleServer(cfg)

    def _drain(signame: str) -> None:
        server.flight_dump(reason=f"signal:{signame}")
        server.begin_drain()

    _signals.install_drain_handlers(stop, _drain, name="serve")

    wall0 = time.perf_counter()
    unsubmitted = 0
    try:
        if warm:
            server.warmup(groups=warm)
        pending = list(reqs)
        while pending and not stop.is_set():
            # Admit what fits, serve a batch, repeat — producer-side
            # backpressure without a second thread (the serve_requests
            # loop, inlined for signal access).
            while pending:
                try:
                    server.submit(pending[0])
                except QueueFull:
                    break
                except ServerDraining:
                    unsubmitted = len(pending)
                    pending = []
                    break
                pending.pop(0)
            req = server.queue.pop()
            if req is not None:
                server._run_batch(req)
        unsubmitted += len(pending)
        server.serve()
    finally:
        server.close()
    wall = time.perf_counter() - wall0

    lat = server.latencies()
    dt = cfg.time.dt
    member_steps = server.stats["member_steps"]
    summary = {
        "metric": "serve_summary",
        "n_requests": len(reqs),
        "completed": server.stats["completed"],
        "evicted": server.stats["evicted"],
        "batches": server.stats["batches"],
        "segments": server.stats["segments"],
        "refills": server.stats["refills"],
        "occupancy_mean": round(server.occupancy_mean, 4),
        "utilization_mean": round(server.utilization_mean, 4),
        "member_steps": member_steps,
        "member_steps_per_sec": round(member_steps / wall, 2),
        "aggregate_sim_days_per_sec": round(
            member_steps * dt / 86400.0 / wall, 4),
        "latency_p50_s": round(float(np.percentile(lat, 50)), 4)
        if len(lat) else None,
        "latency_p99_s": round(float(np.percentile(lat, 99)), 4)
        if len(lat) else None,
        "warmup_compiles": server.stats["warmup_compiles"],
        "steady_recompiles": (server.compile_count()
                              - server.stats["warmup_compiles"]),
        "host_wait_s_total": round(server.stats["host_wait_s"], 4),
        "wall_s": round(wall, 3),
        "requests": {r.id: r.status
                     for r in server.results.values()},
    }
    placement = server.placement_summary()
    if placement is not None:
        summary["placement"] = placement
    # Round 16: each warm bucket's capability proof stamp (plan key,
    # schedule fingerprint, rules version, matrix-coverage verdict).
    summary["bucket_proofs"] = server.bucket_proofs()
    # Round 19: each warm bucket's cost stamp (footprint bytes,
    # flops-vs-analytic ratio, compile seconds, advisory headroom).
    summary["bucket_costs"] = server.bucket_costs()
    memory = server.memory_snapshot()
    if memory is not None:
        summary["memory"] = memory
    if flight_dir:
        summary["flight_dir"] = flight_dir
    if unsubmitted:
        summary["unsubmitted"] = unsubmitted
    print(json.dumps(summary))
    return 0 if server.stats["evicted"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
