"""Re-runnable TT/QTT perf probes — the DESIGN.md tables, one command.

Reproduces the measured tables in docs/DESIGN.md ("Tensor-Train
numerics" round-2 sections) with the same methodology: quiet host
(nothing else running — a concurrent test suite inflated a dense
baseline 2x once, see the benchmark-discipline note), median of reps,
compile excluded.

Usage::

    python scripts/tt_probe.py sphere [n ...]     # factored SWE vs dense twin
    python scripts/tt_probe.py qtt   [N ...]      # QTT diffusion vs dense
    python scripts/tt_probe.py tpu   [n ...]      # factored SWE on the
                                                  # default (device) backend
    python scripts/tt_probe.py sharded [n ...]    # 6-virtual-device factored
                                                  # rate vs single-device +
                                                  # HLO permute-payload bytes
                                                  # vs the dense explicit tier
    python scripts/tt_probe.py qttswe [N ...]     # QTT 2-D SWE vs dense twin
                                                  # (the deck's LANL-124x
                                                  # system in order-d form)

``sphere``/``qtt``/``qttswe``/``sharded`` force CPU f64 (the recorded
tables); ``tpu`` keeps the default backend and f32 (the v5e numbers).
"""

import os
import sys
import time

import numpy as np

import jax

_MODE = sys.argv[1] if len(sys.argv) > 1 else "sphere"
if _MODE in ("sphere", "qtt", "qttswe", "sharded"):
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
if _MODE == "sharded":
    # Virtual devices: effective because the backend is not yet
    # initialized at this point (the reference's setup_sharding set
    # this AFTER first device contact — the ordering bug SURVEY.md §7
    # documents; conftest.py fixes it the same way for tests).
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _median_rate(fn, arg, iters, reps=5):
    """Median-of-reps rate over pipelined dispatch windows.

    Methodology note: this is deliberately the loop the DESIGN.md TT
    tables were measured with — a Python loop of ASYNC dispatches with
    ONE block at the window end (the chained step outputs feed the next
    step, so device work pipelines and the per-dispatch tunnel latency
    is paid once per window, not per step).  It differs from bench.py's
    jit'd-fori methodology, which is required for the production
    stepper's much shorter (~100 us) steps; the TT steps measured here
    are 5-2000 ms, so a window of a few steps is already multi-second.
    """
    out = fn(arg)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        a = arg
        t0 = time.perf_counter()
        for _ in range(iters):
            a = fn(a)
        jax.block_until_ready(a)
        ts.append((time.perf_counter() - t0) / iters)
    return sorted(ts)[len(ts) // 2]


def sphere(sizes, dtype, rank=12):
    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.physics import initial_conditions as ics
    from jaxstream.tt.sphere import factor_panels
    from jaxstream.tt.sphere_swe import (
        covariant_from_cartesian,
        make_dense_sphere_swe,
        make_tt_sphere_swe,
    )

    for n in sizes:
        grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=dtype)
        h_ext, v_ext = ics.williamson_tc2(grid, EARTH_GRAVITY,
                                          EARTH_OMEGA)
        h0 = np.asarray(grid.interior(h_ext), np.float64)
        ua0, ub0 = covariant_from_cartesian(grid, v_ext)
        dt = 30.0 * 256 / n
        dense = jax.jit(make_dense_sphere_swe(grid, dt))
        tt = jax.jit(make_tt_sphere_swe(grid, dt, rank=rank))
        s = tuple(jnp.asarray(np.asarray(x, dtype))
                  for x in (h0, ua0, ub0))
        p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))
        iters = max(4, 4096 // n)
        td = _median_rate(dense, s, iters)
        tq = _median_rate(tt, p, iters)
        print(f"C{n} rank{rank}: dense {td * 1e3:8.2f} ms/step   "
              f"tt {tq * 1e3:8.2f} ms/step   speedup {td / tq:.2f}x",
              flush=True)


def _permute_payload_elements(hlo_text):
    """Sum the output-shape ELEMENT counts of every collective-permute
    in an HLO dump — the per-call inter-device payload of one compiled
    step, dtype-neutral.  Returns ``(elements, count, dtypes_seen)``;
    the dtype set is printed so a mixed-dtype payload can never
    silently skew a recorded ratio."""
    import re

    total = 0
    count = 0
    dtypes = set()
    for m in re.finditer(r"= ([a-z0-9]+)\[([0-9,]*)\][^ ]* collective-permute",
                         hlo_text):
        dtypes.add(m.group(1))
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n
        count += 1
    return total, count, dtypes


def sharded(sizes, rank=12):
    """Round-5 VERDICT ask #6: sharded-TT rate + communication-volume
    evidence on virtual devices, replacing the prose O(n) claim in
    tt/shard.py with numbers (recorded in DESIGN.md)."""
    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.parallel.mesh import setup_sharding, shard_state
    from jaxstream.parallel.sharded_model import make_stepper_for
    from jaxstream.physics import initial_conditions as ics
    from jaxstream.tt.shard import (make_tt_sphere_swe_sharded,
                                    panel_mesh, shard_factored_state)
    from jaxstream.tt.sphere import factor_panels
    from jaxstream.tt.sphere_swe import (covariant_from_cartesian,
                                         make_tt_sphere_swe)

    devs = jax.devices("cpu")
    if len(devs) < 6:
        sys.exit("needs >= 6 virtual CPU devices (XLA_FLAGS was set "
                 "too late — another jax client initialized first)")
    mesh = panel_mesh(devs)
    for n in sizes:
        grid = build_grid(n, halo=2, radius=EARTH_RADIUS,
                          dtype=jnp.float64)
        h_ext, v_ext = ics.williamson_tc2(grid, EARTH_GRAVITY,
                                          EARTH_OMEGA)
        h0 = np.asarray(grid.interior(h_ext), np.float64)
        ua0, ub0 = covariant_from_cartesian(grid, v_ext)
        dt = 30.0 * 256 / n
        p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))

        single = jax.jit(make_tt_sphere_swe(grid, dt, rank=rank))
        shard = jax.jit(make_tt_sphere_swe_sharded(grid, dt, rank, mesh))
        ps = shard_factored_state(p, mesh)

        # AOT-compile once; the timed callable IS this executable (a
        # separate jit dispatch would compile the same graph twice).
        shard_exe = shard.lower(ps).compile()
        tt_el, tt_n, tt_dt = _permute_payload_elements(
            shard_exe.as_text())

        iters = max(4, 1024 // n)
        t1 = _median_rate(single, p, iters)
        t6 = _median_rate(shard_exe, ps, iters)

        # Dense explicit-ppermute comparator (one face per device, the
        # same 4-stage schedule), same n / ssprk3.  Its Pallas RHS is
        # f32-pinned, so it runs on an f32 grid; the volume comparison
        # is in ELEMENTS (bytes / dtype size) to stay dtype-neutral.
        grid32 = build_grid(n, halo=2, radius=EARTH_RADIUS,
                            dtype=jnp.float32)
        h32, v32 = ics.williamson_tc2(grid32, EARTH_GRAVITY,
                                      EARTH_OMEGA)
        model = CovariantShallowWater(grid32, gravity=EARTH_GRAVITY,
                                      omega=EARTH_OMEGA)
        s0 = model.initial_state(h32, v32)
        setup = setup_sharding({
            "parallelization": {"num_devices": 6, "device_type": "cpu",
                                "use_shard_map": True}})
        ss = shard_state(setup, s0)
        dstep = make_stepper_for(model, setup, ss, dt)
        d_el, d_n, d_dt = _permute_payload_elements(
            dstep.lower(ss, jnp.float32(0.0)).compile().as_text())

        print(f"C{n} rank{rank}: single {t1 * 1e3:8.2f} ms/step   "
              f"6-dev {t6 * 1e3:8.2f} ms/step   ratio {t1 / t6:.2f}x",
              flush=True)
        print(f"C{n} permute payload/step: factored {tt_el} elements "
              f"({tt_n} permutes, {sorted(tt_dt)})   dense explicit "
              f"{d_el} elements ({d_n}, {sorted(d_dt)})   "
              f"factored/dense = {tt_el / max(d_el, 1):.4f}",
              flush=True)


def qttswe(sizes, rank=12):
    """Round-5 VERDICT ask #3: the QTT rung table for the 2-D SWE —
    the very system LANL measured 124x on (deck p.3) — with the
    crossover against a dense jnp twin of the same centered scheme.
    The QTT step cost is N-independent (O(d) factorizations at the
    stage bond); the dense step is O(N^2)."""
    from jaxstream.tt.qtt import (make_dense_swe_twin,
                                  make_qtt_swe_stepper,
                                  qtt_compress_separable)

    g, H, f = 9.80616, 1000.0, 1.0e-4
    for N in sizes:
        x = np.arange(N) / N
        dx = 1.0e7 / N                       # 10,000 km domain
        dt = 0.2 * dx / np.sqrt(g * H)
        nu = 1e-4 * dx * dx / dt             # mild grid-scaled filter
        # Separable smooth IC, IDENTICAL for both sides.  Layout is
        # [y, x]: qtt_compress_separable's rows act on y, cols on x —
        # h = 30 sin(2 pi y) cos(4 pi x), u = 5 cos(2 pi y), v = 0.
        y0 = tuple(
            [jnp.asarray(np.asarray(c, np.float64)) for c in cores]
            for cores in (
                qtt_compress_separable(
                    np.stack([30.0 * np.sin(2 * np.pi * x)]),
                    np.stack([np.cos(4 * np.pi * x)]), rank),
                qtt_compress_separable(
                    np.stack([5.0 * np.cos(2 * np.pi * x)]),
                    np.stack([np.ones(N)]), rank),
                qtt_compress_separable(np.stack([np.zeros(N)]),
                                       np.stack([np.zeros(N)]), rank),
            ))
        step = jax.jit(make_qtt_swe_stepper(N, g, H, dx, dt, rank,
                                            f=f, nu=nu))
        tq = _median_rate(step, y0, 4)

        X, Y = np.meshgrid(x, x, indexing="xy")
        h0 = 30.0 * np.sin(2 * np.pi * Y) * np.cos(4 * np.pi * X)
        s0 = tuple(jnp.asarray(q) for q in (
            h0, 5.0 * np.cos(2 * np.pi * Y), np.zeros_like(h0)))

        dstep = jax.jit(make_dense_swe_twin(N, g, H, dx, dt, f=f,
                                            nu=nu))
        td = _median_rate(dstep, s0, max(2, 512 // N))
        print(f"N={N:6d} rank{rank}: dense {td * 1e3:9.2f} ms/step   "
              f"qtt-swe {tq * 1e3:9.2f} ms/step   "
              f"speedup {td / tq:.2f}x", flush=True)


def qtt(sizes, rank=12):
    from jaxstream.tt.qtt import (
        make_qtt_diffusion_stepper,
        qtt_compress,
        qtt_compress_separable,
    )

    for N in sizes:
        dx = 1.0 / N
        dt = 0.1 * dx * dx
        step = jax.jit(make_qtt_diffusion_stepper(N, 1.0, dx, dt, rank))
        x = np.arange(N) / N
        rows = np.stack([np.sin(2 * np.pi * x), np.cos(2 * np.pi * x)])
        cols = np.stack([np.cos(4 * np.pi * x), np.ones(N)])
        if N <= 4096:
            q0 = sum(np.outer(rows[k], cols[k]) for k in range(2))
            y = [jnp.asarray(c) for c in qtt_compress(q0, rank)]
        else:
            y = [jnp.asarray(c)
                 for c in qtt_compress_separable(rows, cols, rank)]
        tq = _median_rate(step, y, 10)
        msg = f"N={N:6d}: qtt {tq * 1e3:8.2f} ms/step"

        def make_dstep(_dx=dx, _dt=dt):
            def dstep(q):
                def lap(v):
                    return (jnp.roll(v, 1, 0) + jnp.roll(v, -1, 0)
                            + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
                            - 4 * v) / (_dx * _dx)
                k1 = q + _dt * lap(q)
                y2 = 0.75 * q + 0.25 * (k1 + _dt * lap(k1))
                return q / 3 + (2.0 / 3.0) * (y2 + _dt * lap(y2))
            return jax.jit(dstep)

        # Dense baseline: MEASURED through N=16384 in f64 (2.1 GB
        # field; fewer reps — the step is seconds); at N=65536 the f64
        # field alone is 34 GB and the roll temporaries exceed host
        # RAM, so the rung is measured in f32 (17 GB field) and
        # labeled — a CONSERVATIVE comparison for the f64 QTT step
        # (f32 dense moves half the bytes an f64 dense would).
        try:
            if N <= 4096:
                td = _median_rate(make_dstep(), jnp.asarray(q0), 10)
                tag = ""
            elif N <= 16384:
                qd = jnp.asarray(sum(np.outer(rows[k], cols[k])
                                     for k in range(2)))
                td = _median_rate(make_dstep(), qd, 2, reps=3)
                tag = " [measured f64]"
            else:
                # Assemble in f32 from the start (an f64 intermediate
                # would be 34 GB by itself); accumulate in place so the
                # peak stays at two 17 GB buffers.
                r32 = rows.astype(np.float32)
                c32 = cols.astype(np.float32)
                q0f = np.outer(r32[0], c32[0])
                q0f += np.outer(r32[1], c32[1])
                qd = jnp.asarray(q0f)
                del q0f
                td = _median_rate(make_dstep(), qd, 1, reps=1)
                gb = N * N * 8 / 2**30
                tag = f" [measured f32: f64 field would be {gb:.0f} GB]"
            msg += (f"   dense {td * 1e3:8.2f} ms/step   "
                    f"speedup {td / tq:.2f}x{tag}")
        except (MemoryError, RuntimeError) as e:
            msg += f"   dense: not measured ({type(e).__name__})"
        print(msg, flush=True)


def main():
    bad = [a for a in sys.argv[2:] if not a.isdigit()]
    if bad:
        sys.exit(f"unparseable size argument(s) {bad}; sizes must be "
                 "plain integers")
    args = [int(a) for a in sys.argv[2:]]
    if _MODE == "sphere":
        sphere(args or [384, 768, 1536], jnp.float64)
    elif _MODE == "qtt":
        qtt(args or [256, 1024, 4096, 16384, 65536])
    elif _MODE == "tpu":
        sphere(args or [256, 512], jnp.float32)
    elif _MODE == "sharded":
        sharded(args or [48, 96])
    elif _MODE == "qttswe":
        qttswe(args or [256, 1024, 4096])
    else:
        sys.exit(f"unknown mode {_MODE!r}; use sphere | qtt | tpu | "
                 "sharded | qttswe")


if __name__ == "__main__":
    main()
