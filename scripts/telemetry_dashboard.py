"""Live operator dashboard over jaxstream telemetry sinks.

Usage::

    python scripts/telemetry_dashboard.py serve.jsonl gateway.jsonl \
        [load.jsonl ...] [--interval 1.0] [--rows 10] [--once] [--json]

Tails one or many ``jaxstream.obs.sink`` JSONL files — a fleet of
serving processes writes one sink each; point the dashboard at all of
them — and renders a live ANSI operator view:

  * **request table** — the most recent completed/evicted requests with
    a per-phase latency bar (queue / pack / compute / host_wait /
    boundary / egress) reassembled from their ``span`` records
    (``serve.trace: true``), plus the in-flight count from the serve
    stream's ``trace_ids``;
  * **rates** — member-steps/s and occupancy sparklines from the
    ``serve`` records, steps/s + drift sparklines from plain
    ``segment`` records when the sink came from a Simulation run;
  * **event feed** — the latest ``guard`` (NaN/CFL evictions, with
    chip attribution) and ``autoscale`` (live bucket-cap resizes)
    records;
  * **per-chip occupancy/utilization** — the latest multi-chip
    placement gauges;
  * **device memory** (round 19, ``serve.memory_watch``) — per-chip
    in-use bars with peak watermarks against capacity, from
    ``memory`` records; plus the **plan cost stamps** panel
    (footprint / compile seconds / flops-vs-analytic band /
    advisory headroom) from ``perf`` records
    (``serve.cost_stamps``);
  * **warm pool** (round 21, ``serve.warm_pool``) — entry hit/miss/
    save counts per degradation rung from ``warmpool`` records, plus
    any advisory-headroom refusals (``headroom`` records).

``--once`` renders one frame and exits; ``--json`` emits that frame as
one machine-readable JSON object instead of ANSI (the form tests and
CI consume).  Records whose kind this tool does not render are never
silently dropped: they surface as a loud ``unrendered kinds`` footer
count (round-17 satellite — same contract as telemetry_report).

stdlib only — this tool must run on a machine with no JAX installed
(it cannot import jaxstream: the package pulls jax at import).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

#: Literal copy of ``jaxstream.obs.trace.PHASE_OF`` (leaf span name ->
#: report phase bucket).  This tool must run without jaxstream
#: installed, so it cannot import the source table;
#: tests/test_trace.py asserts the copies stay identical.
PHASE_OF = {
    "gateway.ingress": "ingress",
    "queue.wait": "queue",
    "serve.pack": "pack",
    "serve.segment": "compute",
    "serve.host_wait": "host_wait",
    "serve.boundary": "boundary",
    "finalize.wait": "egress",
    "result.fetch": "egress",
    "writer.flush": "egress",
    "gateway.egress": "egress",
}

#: Render order + one-letter key + ANSI color of each phase bucket.
PHASES = ("ingress", "queue", "pack", "compute", "host_wait",
          "boundary", "egress")
_PHASE_CH = {"ingress": "i", "queue": "q", "pack": "p", "compute": "C",
             "host_wait": "h", "boundary": "b", "egress": "e"}
_PHASE_COLOR = {"ingress": 90, "queue": 33, "pack": 35, "compute": 32,
                "host_wait": 31, "boundary": 36, "egress": 34}

#: Record kinds this dashboard renders; anything else lands in the
#: loud ``unrendered kinds`` footer instead of vanishing.
RENDERED_KINDS = frozenset({
    "manifest", "span", "serve", "segment", "guard", "autoscale",
    "gateway", "loadgen", "bench", "da", "memory", "perf",
    "flight", "crash", "resume", "warmpool", "headroom",
})

SPARK = "▁▂▃▄▅▆▇█"


def sparkline(vals, width=24):
    """The last ``width`` values as a unicode sparkline."""
    vals = [v for v in vals if v is not None][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(SPARK[int((v - lo) / span * (len(SPARK) - 1))]
                   for v in vals)


class Tailer:
    """Incremental reader of one sink file.

    Remembers its byte offset between polls and only parses COMPLETE
    lines — a writer mid-line (JSONL appends are line-atomic only once
    the newline lands) never produces a half-parsed record; the
    partial tail is re-read on the next poll.
    """

    def __init__(self, path):
        self.path = path
        self.offset = 0

    def poll(self):
        records = []
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self.offset)
                chunk = fh.read()
        except OSError:
            return records              # fleet member not started yet
        end = chunk.rfind(b"\n")
        if end < 0:
            return records
        self.offset += end + 1
        for line in chunk[:end].split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # A torn/corrupt line in one fleet member must not
                # kill the operator view; count it loudly instead.
                records.append({"kind": "_unparseable"})
        return records


class Dashboard:
    """Aggregated fleet state -> one renderable frame."""

    def __init__(self, paths, rows=10):
        self.tailers = [Tailer(p) for p in paths]
        self.rows = rows
        self.requests = {}              # id -> request row (span trees)
        self.order = []                 # completion order of ids
        self.inflight = {}              # trace_id -> last-seen bucket
        self.done_tids = set()          # traces with a root span seen
        self.serve_points = []          # (member_steps/wall, occupancy)
        self.segment_points = []        # (steps_per_sec, max |drift|)
        self.da_cycles = []             # EnKF 'da' cycle records
        self.events = []                # guard + autoscale feed
        self.chips = None               # latest per-chip gauges
        self.memory = None              # latest 'memory' poll
        self.memory_peak = []           # per-chip peak watermarks
        self.memory_unavailable = None  # typed no-allocator-stats note
        self.perf_stamps = {}           # plan -> latest 'perf' stamp
        self.warmpool = {}              # event -> rung -> count (r21)
        self.headroom = []              # advisory-headroom refusals
        self.outcomes = {}              # kind -> status -> count
        self.incidents = []             # flight/crash/resume records
        self.unknown = {}               # kind -> count (loud footer)
        self.manifests = 0

    # ------------------------------------------------------------ ingest
    def poll(self):
        for t in self.tailers:
            for rec in t.poll():
                self._ingest(rec)

    def _ingest(self, rec):
        kind = rec.get("kind")
        if kind == "span":
            self._ingest_span(rec)
        elif kind == "serve":
            wall = rec.get("wall_s") or 0.0
            msps = (rec.get("member_steps", 0) / wall) if wall else None
            self.serve_points.append((msps, rec.get("occupancy")))
            for tid in rec.get("trace_ids", []):
                # The background writer can flush a request's root
                # span BEFORE the serving thread writes the segment
                # record that still lists it resident — a finished
                # trace must never re-enter the in-flight view.
                if tid not in self.done_tids:
                    self.inflight[tid] = rec.get("bucket")
            if rec.get("chip_occupancy"):
                self.chips = {
                    "occupancy": rec["chip_occupancy"],
                    "utilization": rec.get("chip_utilization"),
                    "placement": rec.get("placement"),
                    "devices": rec.get("devices"),
                }
        elif kind == "segment":
            drifts = [abs(v) for v in rec.get("drift", {}).values()]
            self.segment_points.append(
                (rec.get("steps_per_sec"),
                 max(drifts) if drifts else None))
        elif kind == "da":
            self.da_cycles.append(rec)
        elif kind == "memory":
            if rec.get("unavailable"):
                self.memory_unavailable = rec["unavailable"]
            if rec.get("bytes_in_use"):
                self.memory = rec
                peaks = rec.get("peak_bytes") or rec["bytes_in_use"]
                for j, p in enumerate(peaks):
                    if j >= len(self.memory_peak):
                        self.memory_peak.append(p)
                    else:
                        self.memory_peak[j] = max(self.memory_peak[j],
                                                  p)
        elif kind == "perf":
            # Group is part of the identity: two batching groups warm
            # the same B with DIFFERENT executables (oro carries the
            # orography field), and collapsing them would silently
            # overwrite one bucket's stamp with the other's.
            key = (f"{rec.get('plan')}/{rec.get('group')}"
                   f"/B{rec.get('bucket')}")
            self.perf_stamps[key] = rec
        elif kind == "warmpool":
            # Round 21: warm-pool hit/miss/save/corrupt counters per
            # degradation rung — the live answer to "is this fleet
            # paying the compile tax or loading its pool".
            by = self.warmpool.setdefault(str(rec.get("event", "?")),
                                          {})
            rg = str(rec.get("rung", "?"))
            by[rg] = by.get(rg, 0) + 1
        elif kind == "headroom":
            self.headroom.append(rec)
        elif kind in ("guard", "autoscale"):
            self.events.append(rec)
        elif kind in ("gateway", "loadgen"):
            by = self.outcomes.setdefault(kind, {})
            st = rec.get("status", "?")
            by[st] = by.get(st, 0) + 1
        elif kind == "manifest":
            self.manifests += 1
        elif kind in ("flight", "crash", "resume"):
            # Crash forensics (round 20): bundle dumps, crash stamps
            # and resume-lineage records feed the incident panel.
            self.incidents.append(rec)
        elif kind == "bench":
            pass                        # identity lines; not a panel
        else:
            self.unknown[kind] = self.unknown.get(kind, 0) + 1

    def _ingest_span(self, rec):
        row = self.requests.setdefault(
            rec["id"], {"id": rec["id"], "status": None,
                        "latency_s": None, "phases": {}, "bucket": None,
                        "chip": None, "trace_id": rec.get("trace_id")})
        if rec.get("parent_id") is None:        # the root span
            row["status"] = rec.get("status")
            row["latency_s"] = rec.get("duration_s")
            self.done_tids.add(rec.get("trace_id"))
            self.inflight.pop(rec.get("trace_id"), None)
            if rec["id"] in self.order:
                self.order.remove(rec["id"])
            self.order.append(rec["id"])
            return
        phase = PHASE_OF.get(rec.get("name"))
        if phase is None:
            # A leaf span name this copy of the table does not know —
            # schema drift; surface it like any unrendered kind.
            key = f"span:{rec.get('name')}"
            self.unknown[key] = self.unknown.get(key, 0) + 1
            return
        row["phases"][phase] = (row["phases"].get(phase, 0.0)
                                + rec.get("duration_s", 0.0))
        if rec.get("name") == "serve.segment":
            row["bucket"] = rec.get("bucket")
            row["chip"] = rec.get("chip")

    # ------------------------------------------------------------- frame
    def frame(self):
        """The machine-readable frame (the ``--json`` payload)."""
        recent = [self.requests[rid] for rid in self.order[-self.rows:]]
        rates = {
            "member_steps_per_sec": [p[0] for p in self.serve_points],
            "occupancy": [p[1] for p in self.serve_points],
            "steps_per_sec": [p[0] for p in self.segment_points],
            "max_abs_drift": [p[1] for p in self.segment_points],
        }
        return {
            "files": [t.path for t in self.tailers],
            "manifests": self.manifests,
            "requests": recent,
            "n_requests_seen": len(self.requests),
            "inflight": sorted(self.inflight),
            "rates": {k: v[-64:] for k, v in rates.items()},
            "events": self.events[-self.rows:],
            "assimilation": {
                "cycles": [
                    {k: c.get(k) for k in
                     ("cycle", "t", "mode", "spread", "rmse",
                      "spread_post", "rmse_post", "innovation_rms")}
                    for c in self.da_cycles[-self.rows:]],
                "spread_trend": [c.get("spread")
                                 for c in self.da_cycles][-64:],
                "rmse_trend": [c.get("rmse")
                               for c in self.da_cycles][-64:],
            } if self.da_cycles else None,
            "chips": self.chips,
            "memory": ({
                "bytes_in_use": self.memory["bytes_in_use"],
                "limit_bytes": self.memory.get("limit_bytes", []),
                "peak_bytes": list(self.memory_peak),
            } if self.memory is not None else
                ({"unavailable": self.memory_unavailable}
                 if self.memory_unavailable else None)),
            "perf": ([self.perf_stamps[k]
                      for k in sorted(self.perf_stamps)]
                     if self.perf_stamps else None),
            "warm_pool": ({"events": self.warmpool,
                           "refusals": self.headroom[-self.rows:]}
                          if (self.warmpool or self.headroom)
                          else None),
            "outcomes": self.outcomes,
            "incidents": self.incidents[-self.rows:],
            "unrendered_kinds": dict(sorted(self.unknown.items())),
        }


# -------------------------------------------------------------- rendering
def _c(text, code, color):
    return f"\x1b[{code}m{text}\x1b[0m" if color else text


def phase_bar(phases, latency_s, width=28, color=True):
    """One request's phases as a proportional bar.

    Each phase bucket gets ``round(width * share)`` cells of its
    letter (colored when ANSI is on); a phase too short for one cell
    is dropped from the bar but never from the numbers next to it.
    """
    total = latency_s or sum(phases.values()) or 1.0
    out = []
    for ph in PHASES:
        d = phases.get(ph, 0.0)
        n = int(round(width * d / total))
        if n > 0:
            out.append(_c(_PHASE_CH[ph] * n, _PHASE_COLOR[ph], color))
    return "".join(out)


def _fmt_bytes(v):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return (f"{v:.0f}{unit}" if unit == "B"
                    else f"{v:.1f}{unit}")
        v /= 1024.0


def memory_bar(used, peak, limit, width=24):
    """One chip's memory as a bar: filled cells = in use, ``|`` = the
    peak watermark, dots = free capacity (unknown capacity renders
    the used/peak numbers alone)."""
    if not limit:
        return ""
    fill = min(width, int(round(width * used / limit)))
    mark = min(width - 1, int(round(width * peak / limit)))
    cells = ["█"] * fill + ["·"] * (width - fill)
    if mark >= fill:
        cells[mark] = "|"
    return "".join(cells)


def render(frame, color=True):
    lines = []
    title = (f"jaxstream operator view — {len(frame['files'])} sink(s), "
             f"{frame['n_requests_seen']} requests seen, "
             f"{len(frame['inflight'])} in flight")
    lines.append(_c(title, 1, color))
    lines.append("")

    reqs = frame["requests"]
    lines.append(_c("requests (most recent):", 4, color))
    if reqs:
        lines.append(f"  {'id':<14} {'status':<9} {'lat s':>9} "
                     f"{'bucket':>6} {'chip':>4}  phases")
        for r in reqs:
            lat = r["latency_s"]
            bar = phase_bar(r["phases"], lat, color=color)
            ph = " ".join(
                f"{ph[:2]}={r['phases'][ph]:.3f}" for ph in PHASES
                if ph in r["phases"])
            lines.append(
                f"  {r['id']:<14.14} {str(r['status']):<9.9} "
                f"{lat if lat is None else format(lat, '>9.3f')} "
                f"{'' if r['bucket'] is None else r['bucket']:>6} "
                f"{'' if r['chip'] is None else r['chip']:>4}  "
                f"{bar}")
            lines.append(f"  {'':<14} {ph}")
    else:
        lines.append("  (no span records yet — serving with "
                     "serve.trace: true?)")
    lines.append("")

    rates = frame["rates"]
    lines.append(_c("rates:", 4, color))
    for key, label in (("member_steps_per_sec", "member-steps/s"),
                       ("occupancy", "occupancy"),
                       ("steps_per_sec", "steps/s"),
                       ("max_abs_drift", "max |drift|")):
        vals = [v for v in rates.get(key, []) if v is not None]
        if vals:
            lines.append(f"  {label:<15} {sparkline(vals)}  "
                         f"last {vals[-1]:.4g}")
    if frame["chips"]:
        ch = frame["chips"]
        occ = " ".join(f"{v:.2f}" for v in ch["occupancy"])
        line = (f"  per-chip ({ch.get('placement') or '?'} x"
                f"{ch.get('devices') or len(ch['occupancy'])}): "
                f"occ [{occ}]")
        if ch.get("utilization"):
            line += (" util ["
                     + " ".join(f"{v:.2f}" for v in ch["utilization"])
                     + "]")
        lines.append(line)
    for kind, by in sorted(frame["outcomes"].items()):
        parts = " ".join(f"{k}={v}" for k, v in sorted(by.items()))
        lines.append(f"  {kind + ' outcomes':<15} {parts}")
    lines.append("")

    if frame.get("memory"):
        mem = frame["memory"]
        lines.append(_c("device memory (peak watermark |):", 4, color))
        if mem.get("unavailable"):
            lines.append(f"  {mem['unavailable']}")
        for j, used in enumerate(mem.get("bytes_in_use", [])):
            limits = mem.get("limit_bytes", [])
            peaks = mem.get("peak_bytes", [])
            limit = limits[j] if j < len(limits) else 0
            peak = peaks[j] if j < len(peaks) else used
            bar = memory_bar(used, peak, limit)
            tail = (f"{_fmt_bytes(used)} used, peak "
                    f"{_fmt_bytes(peak)}"
                    + (f" / {_fmt_bytes(limit)}" if limit else ""))
            lines.append(f"  chip {j}: {bar}  {tail}")
        lines.append("")

    if frame.get("perf"):
        lines.append(_c("plan cost stamps:", 4, color))
        for p in frame["perf"]:
            mem_p = p.get("memory") or {}
            foot = (_fmt_bytes(mem_p["total_bytes"])
                    if mem_p.get("total_bytes") is not None
                    else "footprint n/a")
            ratio = p.get("flops_ratio")
            band = ("" if p.get("in_band") is None
                    else (" [in band]" if p["in_band"]
                          else " [OUT OF BAND]"))
            hr = p.get("headroom_frac")
            grp = f"/{p['group']}" if p.get("group") else ""
            lines.append(
                f"  {p.get('plan')}{grp}/B{p.get('bucket')}: {foot}, "
                f"compile {p.get('compile_seconds')}s"
                + (f", flops x{ratio}" if ratio is not None else "")
                + band
                + (f", headroom {hr:.1%}" if hr is not None else ""))
        lines.append("")

    if frame.get("warm_pool"):
        wp = frame["warm_pool"]
        lines.append(_c("warm pool (compile tax):", 4, color))
        for ev in sorted(wp.get("events", {})):
            rungs = wp["events"][ev]
            parts = " ".join(f"{r}={n}"
                             for r, n in sorted(rungs.items()))
            lines.append(f"  {ev:<8} {parts}")
        for r in wp.get("refusals", []):
            lines.append(_c(
                f"  headroom refusal: {r.get('action')} bucket "
                f"{r.get('bucket')} (headroom "
                f"{r.get('headroom_frac')} < min "
                f"{r.get('min_headroom_frac')})", 33, color))
        lines.append("")

    if frame.get("assimilation"):
        da = frame["assimilation"]
        lines.append(_c("assimilation (EnKF cycle):", 4, color))
        lines.append(f"  {'cycle':>5} {'spread':>9} {'rmse':>9} "
                     f"{'spread+':>9} {'rmse+':>9} {'innov':>9}")
        for c in da["cycles"]:
            lines.append(
                f"  {c['cycle']:>5} {c['spread']:>9.4f} "
                f"{c['rmse']:>9.4f} {c['spread_post']:>9.4f} "
                f"{c['rmse_post']:>9.4f} {c['innovation_rms']:>9.4f}")
        spread = [v for v in da["spread_trend"] if v is not None]
        rmse = [v for v in da["rmse_trend"] if v is not None]
        if spread:
            lines.append(f"  {'spread':<15} {sparkline(spread)}  "
                         f"last {spread[-1]:.4g}")
        if rmse:
            lines.append(f"  {'rmse':<15} {sparkline(rmse)}  "
                         f"last {rmse[-1]:.4g}")
        lines.append("")

    lines.append(_c("events (guard/autoscale):", 4, color))
    if frame["events"]:
        for ev in frame["events"]:
            if ev["kind"] == "guard":
                who = ("" if ev.get("member") is None
                       else f" member {ev['member']}")
                who += ("" if ev.get("chip") is None
                        else f" chip {ev['chip']}")
                lines.append(_c(
                    f"  guard step {ev.get('step')}: {ev.get('event')}"
                    f"{who} (value {ev.get('value')})", 31, color))
            else:
                lines.append(
                    f"  autoscale bucket {ev.get('from_bucket')} -> "
                    f"{ev.get('to_bucket')} (queue "
                    f"{ev.get('queue_depth')}, {ev.get('reason')})")
    else:
        lines.append("  none")

    if frame.get("incidents"):
        lines.append("")
        lines.append(_c("incidents (flight recorder / crash "
                        "forensics):", 4, color))
        for inc in frame["incidents"]:
            kind = inc.get("kind")
            if kind == "crash":
                lines.append(_c(
                    f"  CRASH bundle {inc.get('bundle')} "
                    f"({inc.get('reason')}) -> {inc.get('path')}",
                    31, color))
            elif kind == "resume":
                lines.append(_c(
                    f"  resume from bundle {inc.get('bundle')} "
                    f"@ checkpoint step {inc.get('checkpoint_step')} "
                    f"(now at step {inc.get('step')})", 33, color))
            else:                       # "flight" dump stamp
                lines.append(
                    f"  flight dump: {inc.get('events')} events, "
                    f"{inc.get('threads')} thread ring(s), "
                    f"{inc.get('dropped')} dropped")
        lines.append(_c("  (reconstruct: python scripts/postmortem.py "
                        "<bundle-dir> --sink <sink.jsonl>)", 90, color))

    if frame["unrendered_kinds"]:
        parts = ", ".join(f"{k} x{v}" for k, v in
                          frame["unrendered_kinds"].items())
        lines.append("")
        lines.append(_c(f"!! unrendered kinds (this dashboard does not "
                        f"know them — schema drift?): {parts}",
                        33, color))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live ANSI operator dashboard over jaxstream "
                    "telemetry sinks (one or many files — a fleet).")
    ap.add_argument("paths", nargs="+",
                    help="sink JSONL files to tail (obs.sink format)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds (live mode)")
    ap.add_argument("--rows", type=int, default=10,
                    help="request-table / event-feed depth")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (tests/CI)")
    ap.add_argument("--json", action="store_true",
                    help="emit the frame as one JSON object (implies "
                         "--once unless --interval'd explicitly)")
    ap.add_argument("--no-color", action="store_true",
                    help="plain text (no ANSI escapes)")
    args = ap.parse_args(argv)

    dash = Dashboard(args.paths, rows=args.rows)
    color = not args.no_color and sys.stdout.isatty()
    if args.once or args.json:
        dash.poll()
        if args.json:
            print(json.dumps(dash.frame()))
        else:
            print(render(dash.frame(), color=color))
        return 0
    try:
        while True:
            dash.poll()
            # Clear + home, then one frame: a single write per refresh
            # keeps partially-drawn frames off slow terminals.
            sys.stdout.write("\x1b[2J\x1b[H"
                             + render(dash.frame(), color=color)
                             + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
