"""Run an EnKF assimilation cycle (jaxstream.da, round 18).

Usage::

    python scripts/assimilate.py [config.yaml]
        [--mode inprocess|gateway] [--free-baseline]
        [--sink run.jsonl] [--json]

Drives :func:`jaxstream.da.run_cycle` (in-process, the default) or
:func:`jaxstream.da.run_cycle_gateway` — the latter starts an
in-process loopback :class:`jaxstream.gateway.Gateway` over the same
config (``serve.buckets`` pinned to the single ``members + 1`` bucket
so the persistent member batch packs deterministically) and runs the
cycle as a network client: per-member result fetch, analysis update,
raw-array re-submission.

``--free-baseline`` also runs the free (no-assimilation) ensemble
under identical seeds and reports the forecast claim — the cycled
ensemble-mean RMSE must beat the free ensemble's; exit status 1 when
it does not.  Prints exactly ONE JSON summary line.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run(argv=None):
    ap = argparse.ArgumentParser(
        description="Run a jaxstream EnKF assimilation cycle.")
    ap.add_argument("config", nargs="?", default=None,
                    help="YAML config (grid/time/model/ensemble/da "
                         "blocks); defaults apply when omitted")
    ap.add_argument("--mode", choices=("inprocess", "gateway"),
                    default="inprocess")
    ap.add_argument("--free-baseline", action="store_true",
                    help="also run the free ensemble and gate the "
                         "forecast claim (cycled RMSE < free RMSE)")
    ap.add_argument("--sink", default=None,
                    help="telemetry JSONL path for 'da' records "
                         "(overrides da.sink)")
    ap.add_argument("--json", action="store_true",
                    help="(accepted for symmetry; the summary is "
                         "always one JSON line)")
    args = ap.parse_args(argv)

    from jaxstream.config import load_config
    from jaxstream.da import run_cycle, run_cycle_gateway

    cfg = load_config(args.config)

    def free_sink(path):
        return (path + ".free") if path else None

    if args.mode == "gateway":
        from jaxstream.gateway import Gateway

        # One warm bucket of exactly members+1 slots: the persistent
        # member batch (members + the hidden truth) always packs into
        # the same executable, which is what makes the cycle outputs
        # byte-deterministic across runs.
        bucket = cfg.ensemble.members + 1
        cfg = dataclasses.replace(
            cfg, serve=dataclasses.replace(cfg.serve,
                                           buckets=str(bucket)))
        gw = Gateway(cfg, host="127.0.0.1", port=0)
        gw.start()
        try:
            summary = run_cycle_gateway(cfg, host="127.0.0.1",
                                        port=gw.port,
                                        sink=args.sink)
            free = (run_cycle_gateway(cfg, host="127.0.0.1",
                                      port=gw.port, assimilate=False,
                                      sink=free_sink(args.sink))
                    if args.free_baseline else None)
        finally:
            gw.close()
    else:
        summary = run_cycle(cfg, sink=args.sink)
        free = (run_cycle(cfg, assimilate=False,
                          sink=free_sink(args.sink))
                if args.free_baseline else None)

    out = dict(summary)
    code = 0
    if free is not None:
        out["free_final_rmse"] = free["final_rmse"]
        out["free_mean_rmse"] = free["mean_rmse"]
        out["rmse_reduction"] = (free["final_rmse"]
                                 - summary["final_rmse"])
        out["beats_free_run"] = bool(
            summary["final_rmse"] < free["final_rmse"])
        if not out["beats_free_run"]:
            code = 1
    return code, out


def main(argv=None) -> int:
    code, out = run(argv)
    print(json.dumps(out))
    return code


if __name__ == "__main__":
    sys.exit(main())
