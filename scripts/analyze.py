"""Trace-time contract checker (CLI front end for jaxstream.analysis).

Statically verifies the paper's race-free halo-exchange claim and the
compiled-stepper invariants across the current composition matrix —
see :mod:`jaxstream.analysis` for what each pass proves.  Exit status
0 = every contract holds; 1 = violations (listed on stdout, or in the
``violations`` array under ``--json``).

Usage::

    python scripts/analyze.py [n] [--json] [--schedules-only]
                              [--no-compile]
                              [--fixture <name>]

``[n]`` is the face size of the check grid (default 12 — the matrix
is resolution-independent; a bigger n only costs trace time).
``--schedules-only`` runs just the pure schedule pass (milliseconds,
no devices — the pre-commit mode).  ``--no-compile`` skips the two
checks that need XLA compiles (donation aliasing, member-parallel
zero-wire HLO), keeping the run trace-only.  ``--fixture`` verifies
one of the seeded-broken regression fixtures instead (broken
schedules, an illegal capability plan, a corrupted proof stamp)
(:mod:`jaxstream.analysis.fixtures`): the checker must FAIL it, so the
command exits nonzero — CI asserts both fixtures trip and every real
schedule passes, proving the pass has teeth in the same gate that
trusts it.

``--json`` prints exactly ONE JSON line: ``ok``, ``checks_run``,
``violations`` and — for the full mode — per-variant ``facts``
(collective counts vs the comm_probe analytic plans, payload bytes,
schedule fingerprints).  ``bench.py`` embeds the same record as every
run's ``contract_check`` stamp, and the tier-1 gate runs this file's
checks through tests/test_analysis.py.

The stepper matrix needs >= 6 CPU devices; running this file as
``__main__`` sets the virtual-host-device flag before JAX's backends
initialize (in-process callers rely on their own pool, e.g. the test
conftest's 8 virtual devices).
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def run(argv):
    """Parse ``argv`` and run the requested pass.

    Returns ``(exit_code, result_dict, report)`` — importable so
    ``bench.py`` and the tests reuse the CLI semantics in-process
    without a subprocess.
    """
    args = list(argv)
    as_json = "--json" in args
    schedules_only = "--schedules-only" in args
    no_compile = "--no-compile" in args
    fixture = None
    n = 12
    consumed = set()
    for i, a in enumerate(args):
        if i in consumed or a in ("--json", "--schedules-only",
                                  "--no-compile"):
            continue
        if a == "--fixture":
            if i + 1 >= len(args) or args[i + 1].startswith("--"):
                print("usage: analyze.py --fixture <name> (one of "
                      "jaxstream.analysis.fixtures.FIXTURES)",
                      file=sys.stderr)
                raise SystemExit(2)
            fixture = args[i + 1]
            consumed.add(i + 1)
        elif a.isdigit():
            n = int(a)
        else:
            # A typo'd flag must not silently run a different (more
            # expensive, or weaker) mode with exit 0.
            print(f"analyze.py: unknown argument {a!r}; usage: "
                  f"analyze.py [n] [--json] [--schedules-only] "
                  f"[--no-compile] [--fixture <name>]",
                  file=sys.stderr)
            raise SystemExit(2)

    from jaxstream.analysis import contracts
    from jaxstream.analysis import fixtures as fx

    if fixture is not None:
        if fixture not in fx.FIXTURES:
            print(f"unknown fixture {fixture!r}; valid: "
                  f"{list(fx.FIXTURES)}", file=sys.stderr)
            raise SystemExit(2)
        report = fx.run_fixture(fixture, n=n)
        result = {"mode": f"fixture:{fixture}", **report.to_json()}
        # A fixture is a seeded break: violations are the EXPECTED
        # outcome, and the nonzero exit is what CI asserts.  Exit 0
        # here would mean the checker failed to catch the break.
        return (1 if not report.passed else 0), result, report
    if schedules_only:
        report = contracts.check_schedules(n=n)
        result = {"mode": "schedules", **report.to_json()}
    else:
        report, facts = contracts.run_all(
            n=n, include_compile=not no_compile)
        result = {"mode": "full", **report.to_json(), "facts": facts}
    return (0 if report.passed else 1), result, report


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    code, result, report = run(argv)
    if "--json" in argv:
        print(json.dumps(result))
    else:
        print(report.format())
    return code


if __name__ == "__main__":
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    sys.exit(main())
