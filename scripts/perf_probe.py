"""Single-chip perf probe: steady-state step rate of fused-stepper variants.

The measurement methodology of DESIGN.md ("Step-time methodology"): jit a
``fori_loop`` of the step, size the window for multi-second runs, time
the second call.  Usage::

    python scripts/perf_probe.py [n] [variant ...] [--stamp]

Variants: ``mc`` / ``minmod`` / ``none`` / ``vanleer`` (limiter choice
on the compact covariant stepper), ``bf16`` (bf16 carry, h stored as
anomaly), ``int16`` (int16 fixed-point carry, magic-constant rounding),
``mixed16`` (h int16 fixed-point + u bf16 — mass-neutral 16-bit),
``noseam`` (seam imposition ablated — measurement only, breaks
conservation).  Default: ``mc``.

Round 19: every variant line carries its roofline from the SAME cost
accounting bench uses (``jaxstream.obs.perf.roofline_json`` — one
definition; 16-bit carries billed at the corrected ``carry_bytes=2``
model, not the old ``bytes * 0.5``).  ``--stamp`` additionally
compiles each variant's step ahead-of-time and prints its full cost
stamp (footprint bytes, compile seconds, XLA-vs-analytic flop ratio —
``measure_cost``; one extra XLA compile per variant).
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water_cov import CovariantShallowWater
from jaxstream.physics.initial_conditions import williamson_tc5
from jaxstream.stepping import integrate


def measure(step, y, dt, k1=3000, k2=15000):
    """Dispatch-overhead-free steady-state rate (shared methodology:
    :func:`jaxstream.utils.profiling.steady_state_rate`)."""
    from jaxstream.utils.profiling import steady_state_rate

    run = jax.jit(lambda y, k: integrate(step, y, 0.0, k, dt),
                  donate_argnums=0)
    y, _ = run(y, 10)
    jax.block_until_ready(y["h"])
    rate, y = steady_state_rate(lambda y, k: run(y, k)[0], y, k1=k1, k2=k2)
    assert np.all(np.isfinite(np.asarray(y["h"])))
    return rate


def main():
    args = sys.argv[1:]
    stamp = "--stamp" in args
    args = [a for a in args if a != "--stamp"]
    n = int(args[0]) if args and args[0].isdigit() else 384
    variants = [a for a in args if not a.isdigit()] or ["mc"]
    dt = 60.0

    from jaxstream.obs import perf as obs_perf

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)

    for v in variants:
        limiter = v if v in ("mc", "minmod", "none", "vanleer") else "mc"
        kw = {}
        if v == "noseam":
            kw["_ablate_seam"] = True
        model = CovariantShallowWater(
            grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
            backend="pallas", limiter=limiter)
        st = model.initial_state(h_ext, v_ext)
        if v in ("bf16", "int16", "mixed16"):
            # mixed16 (round 5): h int16 fixed-point (mass stays at the
            # accuracy-neutral int16 level — the bf16 h-anomaly's mass
            # leak lives entirely in h) + u bf16 (the native-convert
            # encoding that carried the round-2 ladder's speed).
            off = float(0.5 * (jnp.min(st["h"]) + jnp.max(st["h"])))
            cd = {"bf16": (jnp.bfloat16,) * 2,
                  "int16": (jnp.int16,) * 2,
                  "mixed16": (jnp.int16, jnp.bfloat16)}[v]
            hs = 1.0 if v == "bf16" else 0.0625
            us = float(grid.radius) / 256.0 if v == "int16" else 1.0
            kw.update(carry_dtype=cd, h_offset=off, h_scale=hs, u_scale=us)
            step = model.make_fused_step(dt, **kw)
            y = model.encode_carry(model.compact_state(st), cd, off, hs, us)
        else:
            step = model.make_fused_step(dt, **kw)
            y = model.compact_state(st)
        rate = measure(step, y, dt)
        print(f"C{n} {v:8s}: {rate:8.1f} steps/s  "
              f"({rate * dt / 86400.0:.3f} sim-days/s)")
        # Round 19: the roofline from the ONE cost-accounting
        # definition (obs.perf; bench's variant entries use the same
        # helper) — 16-bit carries at the corrected carry_bytes=2.
        carry_bytes = 2 if v in ("bf16", "int16", "mixed16") else None
        try:
            rl = obs_perf.roofline_json(rate, n,
                                        carry_bytes=carry_bytes)
            print(f"    roofline: {rl['achieved_tflops']} TFLOP/s "
                  f"({rl['pct_of_compute_roof']}% of VPU roof), "
                  f"{rl['achieved_gbps']} GB/s "
                  f"({rl['pct_of_hbm']}% of HBM), AI {rl['ai']}")
        except Exception as e:
            print(f"    roofline unavailable ({type(e).__name__}: {e})")
        if stamp:
            st_cost = obs_perf.measure_cost(
                step, y, jnp.float32(0.0),
                plan_key=f"perf_probe:{v}_C{n}",
                analytic=obs_perf.analytic_cost(
                    n, carry_bytes=carry_bytes),
                xla_visible=False)   # fused Pallas: XLA can't see it
            print(f"    {st_cost}")


if __name__ == "__main__":
    main()
