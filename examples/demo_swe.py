"""Shallow-water demos: Williamson TC2 (steady) / TC5 (mountain).

Usage: python examples/demo_swe.py [n] [tc2|tc5] [days]
"""

import sys
import time

import numpy as np
import jax

sys.path.insert(0, ".")

from jaxstream.config import EARTH_GRAVITY as G, EARTH_OMEGA as OM, EARTH_RADIUS as A
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.shallow_water import ShallowWater
from jaxstream.physics.initial_conditions import williamson_tc2, williamson_tc5
from jaxstream.utils.diagnostics import error_norms, total_energy, total_mass


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    case = sys.argv[2] if len(sys.argv) > 2 else "tc2"
    days = float(sys.argv[3]) if len(sys.argv) > 3 else 5.0
    grid = build_grid(n, halo=2, radius=A)

    if case == "tc2":
        h0, v0 = williamson_tc2(grid, G, OM)
        model = ShallowWater(grid, G, OM)
        b_int = 0.0
    else:
        h0, v0, b = williamson_tc5(grid, G, OM)
        model = ShallowWater(grid, G, OM, b_ext=b)
        b_int = grid.interior(b)

    state = model.initial_state(h0, v0)
    ref_h = state["h"]
    m0 = float(total_mass(grid, state["h"]))
    e0 = float(total_energy(grid, state["h"], state["v"], G, b_int))

    c = np.sqrt(G * float(jax.numpy.max(state["h"]))) + 40.0
    dt = 0.4 * A * grid.dalpha / c
    nsteps = int(days * 86400 / dt)
    print(f"{case.upper()} C{n}: dt={dt:.0f}s, {nsteps} steps ({days} days) "
          f"on {jax.devices()[0].platform}")
    wall = time.time()
    state, t = model.run(state, nsteps, dt)
    jax.block_until_ready(state)
    wall = time.time() - wall

    m1 = float(total_mass(grid, state["h"]))
    e1 = float(total_energy(grid, state["h"], state["v"], G, b_int))
    print(f"wall {wall:.1f}s ({nsteps / wall:.0f} steps/s, "
          f"{days / (wall / 86400) / 86400:.1f} sim-days/sec)")
    print(f"h range [{float(state['h'].min()):.0f}, {float(state['h'].max()):.0f}] m")
    print(f"mass drift {(m1 - m0) / m0:.2e}, energy drift {(e1 - e0) / e0:.2e}")
    if case == "tc2":
        err = {k: float(v) for k, v in error_norms(grid, state["h"], ref_h).items()}
        print(f"TC2 steady-state error norms: {err}")


if __name__ == "__main__":
    main()
