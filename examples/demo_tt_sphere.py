"""Tensor-Train numerics ON THE CUBED SPHERE: the deck's thesis, measured.

Runs Williamson TC2 (steady geostrophic flow — any drift is numerical
error) two ways and times both under ``jax.jit``:

  * **dense twin** — the same vector-invariant covariant discretization
    on materialized ``(6, n, n)`` fields; the parity oracle and the
    honest speed baseline.
  * **TT (factored panels)** — every prognostic a rank-r pair
    ``q = A @ B``; reconstructed-strip halo exchange with the
    exact-geometry seam resampling, Khatri-Rao products rounded by
    batched cross/ACA.  Nothing ``(n, n)`` is ever materialized.

Reports per-step wall time for both, the speedup, the compression
ratio, and each run's TC2 height drift.

Run: python examples/demo_tt_sphere.py [n] [rank] [steps]
     (defaults 256, 12, 20; crossover vs the dense twin is ~C700-800 —
      see docs/DESIGN.md "Round 2 (cont.)")
"""

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.physics import initial_conditions as ics
from jaxstream.tt.sphere import factor_panels, unfactor_panels
from jaxstream.tt.sphere_swe import (
    covariant_from_cartesian,
    make_dense_sphere_swe,
    make_tt_sphere_swe,
)


def bench(step, state, steps):
    state_out = step(state)
    jax.block_until_ready(state_out)
    t0 = time.perf_counter()
    for _ in range(steps):
        state = step(state)
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / steps, state


def main():
    args = sys.argv[1:]
    n = int(args[0]) if len(args) > 0 else 256
    rank = int(args[1]) if len(args) > 1 else 12
    steps = int(args[2]) if len(args) > 2 else 20
    dt = 30.0 * 256 / n

    print(f"TC2 on C{n}, rank {rank}, {steps} steps of dt={dt:.0f}s "
          f"on {jax.devices()[0]}")
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = ics.williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    h0 = np.asarray(grid.interior(h_ext), np.float64)
    ua0, ub0 = covariant_from_cartesian(grid, v_ext)

    dense = jax.jit(make_dense_sphere_swe(grid, dt))
    tt = jax.jit(make_tt_sphere_swe(grid, dt, rank=rank))
    s = tuple(jnp.asarray(np.asarray(x, np.float32))
              for x in (h0, ua0, ub0))
    p = tuple(factor_panels(x, rank) for x in (h0, ua0, ub0))

    td, s = bench(dense, s, steps)
    tt_t, p = bench(tt, p, steps)

    drift = lambda h: (np.linalg.norm(np.asarray(h, np.float64) - h0)
                       / np.linalg.norm(h0))
    comp = (2 * rank * n) / (n * n)
    print(f"  dense : {td * 1e3:8.2f} ms/step   h drift {drift(s[0]):.2e}")
    print(f"  TT    : {tt_t * 1e3:8.2f} ms/step   "
          f"h drift {drift(unfactor_panels(p[0])):.2e}")
    print(f"  speedup {td / tt_t:.2f}x   state compression {comp:.3f} "
          f"({1 / comp:.0f}:1)")


if __name__ == "__main__":
    main()
