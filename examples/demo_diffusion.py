"""Lima-flag thermal diffusion demo (reference deck p.12/p.17).

Checkerboard 1-1000 K heat source on the north panel, diffused for a few
weeks; prints conservation and symmetry diagnostics.  Runs on whatever the
default JAX device is (the real TPU under axon; CPU elsewhere).
"""

import sys
import time

import numpy as np
import jax

sys.path.insert(0, ".")

from jaxstream.config import EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.diffusion import ThermalDiffusion
from jaxstream.physics.initial_conditions import checkerboard
from jaxstream.utils.diagnostics import total_mass


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS)
    kappa = 1.0e7  # m^2/s, exaggerated for a visible few-week spread
    model = ThermalDiffusion(grid, kappa)
    state = model.initial_state(checkerboard(grid, face=4))
    t0_heat = float(total_mass(grid, state["T"]))

    dt = 0.2 * (EARTH_RADIUS * grid.dalpha) ** 2 / kappa  # diffusive CFL
    days = 26.7
    nsteps = int(days * 86400 / dt)
    print(f"C{n}, kappa={kappa:.1e} m^2/s, dt={dt:.0f}s, {nsteps} steps "
          f"({days} days) on {jax.devices()[0].platform}")
    wall = time.time()
    state, t = model.run(state, nsteps, dt, scheme="rk4")
    jax.block_until_ready(state)
    wall = time.time() - wall

    T = np.asarray(state["T"])
    heat = float(total_mass(grid, state["T"]))
    print(f"wall {wall:.1f}s ({nsteps / wall:.0f} steps/s)")
    print(f"T range [{T.min():.2f}, {T.max():.2f}] K (started [1, 1000])")
    print(f"heat conservation drift: {(heat - t0_heat) / t0_heat:.2e}")
    print("per-face mean K:", np.round(T.mean(axis=(1, 2)), 2))
    adj = T.mean(axis=(1, 2))[:4]
    print(f"equatorial-face symmetry spread: {adj.max() - adj.min():.2e} K")


if __name__ == "__main__":
    main()
