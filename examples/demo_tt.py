"""Tensor-Train compressed diffusion: the deck's p.19 story, measured.

Evolves a 2-D periodic diffusion problem two ways and times both under
``jax.jit`` + ``lax.fori_loop`` (compile excluded, multi-second windows):

  * **dense** — the honest memory-bound baseline: (N, N) field, roll-based
    5-point FV stencil, SSPRK3.  ~30 flops/cell/step but 3 full-field
    read/write passes — exactly the AI ~ 0.25 flops/byte regime of the
    deck's roofline chart (p.19).
  * **TT (static rank)** — the field never exists: a rank-r factored TT
    ``q = A @ B`` (O(N r) parameters), stepped by
    :func:`jaxstream.tt.solver.make_tt_stepper_static` — stack scaled
    factor pairs, QR/SVD-round back to rank r, all shapes static, the
    whole step one compiled XLA program of small matmuls (the deck's
    "r x r multiplies, ideal for TPU/GPU", p.5).

Reports compression, wall-clock for both, the measured speedup, and the
L2 error of the TT run against the dense oracle.

Run: python examples/demo_tt.py [N] [rank]    (defaults 1024, 16 — the
deck's "~20x at N=1024" operating point, p.19)
"""

import os
import sys
import time

import numpy as np

import jax

# The accuracy story wants f64 (f32 TT truncation floors near 1e-6); the
# demo is a CPU measurement — a remote accelerator would time the tunnel,
# not the math (sitecustomize initializes JAX before env vars are read,
# so set both via config).
jax.config.update("jax_enable_x64", True)
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from jaxstream.tt.solver import (  # noqa: E402
    factor_field,
    make_tt_stepper_static,
    unfactor_field,
)


def main(n: int = 1024, rank: int = 16, nsteps: int = 200):
    kappa = 1.0e-3
    dx = 1.0 / n
    dt = 0.2 * dx * dx / kappa

    x = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(x, x, indexing="ij")
    q0 = (np.exp(-((X - 0.3) ** 2 + (Y - 0.4) ** 2) / 0.005)
          + 0.5 * np.sin(2 * np.pi * X) * np.sin(4 * np.pi * Y) ** 2)
    q0 = jnp.asarray(q0, jnp.float64)

    # ---- dense baseline: roll-based 5-point stencil, SSPRK3 --------------
    c = kappa / (dx * dx)

    def lap(q):
        return c * (jnp.roll(q, 1, 0) + jnp.roll(q, -1, 0)
                    + jnp.roll(q, 1, 1) + jnp.roll(q, -1, 1) - 4.0 * q)

    def dense_step(q):
        y1 = q + dt * lap(q)
        y2 = 0.75 * q + 0.25 * (y1 + dt * lap(y1))
        return q / 3.0 + (2.0 / 3.0) * (y2 + dt * lap(y2))

    dense_run = jax.jit(
        lambda q, k: jax.lax.fori_loop(0, k, lambda i, q: dense_step(q), q),
        static_argnums=1)
    qd = jax.block_until_ready(dense_run(q0, nsteps))       # compile+warm
    t0 = time.perf_counter()
    qd = jax.block_until_ready(dense_run(q0, nsteps))
    t_dense = time.perf_counter() - t0
    qd2 = jax.block_until_ready(dense_run(qd, nsteps))      # oracle at 2T

    # ---- TT path: static-rank factored stepper, same discretization ------
    # The 1-D stencil acts on factor columns/rows by rolls: O(N r) per
    # operator application (a dense (N, N) stencil matrix would be
    # O(N^2 r) and lose to the stencil baseline outright).
    def d2_cols(A):        # second difference down the length-N columns
        return c * (jnp.roll(A, 1, 0) + jnp.roll(A, -1, 0) - 2.0 * A)

    def d2_rows(B):        # second difference along the length-N rows
        return c * (jnp.roll(B, 1, 1) + jnp.roll(B, -1, 1) - 2.0 * B)

    step = make_tt_stepper_static(d2_cols, d2_rows, dt, rank)
    tt_run = jax.jit(
        lambda q, k: jax.lax.fori_loop(0, k, lambda i, q: step(q), q),
        static_argnums=1)
    qt0 = factor_field(q0, rank)
    qt = jax.block_until_ready(tt_run(qt0, nsteps))         # compile+warm
    t0 = time.perf_counter()
    qt = jax.block_until_ready(tt_run(qt0, nsteps))
    t_tt = time.perf_counter() - t0
    qt2 = jax.block_until_ready(tt_run(qt, nsteps))

    err = float(jnp.linalg.norm(unfactor_field(qt2) - qd2)
                / jnp.linalg.norm(qd2))
    dense_params = n * n
    tt_params = 2 * n * rank
    print(f"N={n} rank={rank}  steps={nsteps} (timed window), dt={dt:.3g}")
    print(f"compression: {dense_params} -> {tt_params} parameters "
          f"({dense_params / tt_params:.1f}x)")
    print(f"wall: dense {t_dense * 1e3:.1f} ms, TT {t_tt * 1e3:.1f} ms  "
          f"-> TT speedup {t_dense / t_tt:.1f}x")
    print(f"L2 relative error vs dense oracle (2x window): {err:.2e}")
    assert err < 1e-6, err


def main_swe(n: int = 2048, rank: int = 12, nsteps: int = 50,
             rounding: str = "sketch"):
    """Nonlinear factored-form SWE (jaxstream.tt.swe2d) vs dense stencil.

    The deck's cited LANL regime (nonlinear Cartesian-2D SWE in TT form,
    accuracy preserved).  Quadratic terms are Khatri-Rao products rounded
    back to rank r; ``rounding='cross'`` (the LANL ACA route,
    jaxstream.tt.cross) removes every eigh/SVD from the step — measured
    on this machine's single CPU core (min of reps, 50 steps):

        N=1024: rank 6 -> 17.3x (err 6.8e-8), rank 8 -> 10.8x (1.6e-9)
        N=2048: rank 6 -> 35.4x (2.8e-8),     rank 8 -> 21.6x (1.2e-9)

    i.e. the deck p.19 ~20x estimate is met at N=2048 for ranks <= 8 and
    approached at N=1024; the remaining wall at N=1024 is the rounding's
    sequential small-matvec (BLAS-2) floor on a single core — see
    DESIGN.md.
    """
    from jaxstream.tt.swe2d import (
        make_dense_swe_stepper,
        make_tt_swe_stepper,
        sw_factor,
        sw_unfactor,
    )

    g0, h0 = 9.81, 1000.0
    L = 1.0e6
    dx = L / n
    c = np.sqrt(g0 * h0)
    dt = 0.3 * dx / c
    nu = 0.02 * dx * dx / dt

    x = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(x, x, indexing="ij")
    h = jnp.asarray(
        h0 + 10.0 * np.exp(-((X - 0.5 * L) ** 2 + (Y - 0.4 * L) ** 2)
                           / (0.05 * L) ** 2))
    z = jnp.zeros((n, n), jnp.float64)

    dstep = make_dense_swe_stepper(dx, dx, dt, g0, nu=nu)
    dense = jax.jit(lambda s, k: jax.lax.fori_loop(
        0, k, lambda i, s: dstep(s), s), static_argnums=1)
    s0 = (h, z, z)
    ref = jax.block_until_ready(dense(s0, nsteps))
    t0 = time.perf_counter()
    ref = jax.block_until_ready(dense(s0, nsteps))
    t_dense = time.perf_counter() - t0

    step = make_tt_swe_stepper(n, n, dx, dx, dt, g0, rank, nu=nu,
                               rounding=rounding)
    tt_run = jax.jit(lambda s, k: jax.lax.fori_loop(
        0, k, lambda i, s: step(s), s), static_argnums=1)
    st = tuple(sw_factor(q, rank) for q in s0)
    out = jax.block_until_ready(tt_run(st, nsteps))
    t0 = time.perf_counter()
    out = jax.block_until_ready(tt_run(st, nsteps))
    t_tt = time.perf_counter() - t0

    err = float(jnp.linalg.norm(sw_unfactor(out[0]) - ref[0])
                / jnp.linalg.norm(ref[0] - h0))
    print(f"SWE N={n} rank={rank} steps={nsteps} [{rounding}]: dense "
          f"{t_dense * 1e3:.1f} ms, TT {t_tt * 1e3:.1f} ms -> "
          f"{t_dense / t_tt:.1f}x; h-anomaly L2 err {err:.2e}")
    assert err < 0.1, err


if __name__ == "__main__":
    if len(sys.argv) > 1:
        main(int(sys.argv[1]),
             int(sys.argv[2]) if len(sys.argv) > 2 else 16)
    else:
        # Scaling story: dense work is O(N^2), TT work is O(N r^2) plus
        # N-independent small factorizations — the TT advantage is the
        # *slope* (deck p.19's argument; its ~20x figure is this regime).
        main(1024, 16, nsteps=200)
        print()
        main(4096, 16, nsteps=25)
        print()
        main_swe(2048, 12, nsteps=50)
        print()
        main_swe(2048, 8, nsteps=50, rounding="cross")
        print()
        main_swe(1024, 6, nsteps=50, rounding="cross")
