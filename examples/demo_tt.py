"""Tensor-Train compressed diffusion: the deck's p.19 story, runnable.

Evolves a 2-D periodic diffusion problem two ways — dense (N x N field,
FV stencils) and fully compressed (TT cores, step-and-truncate SSPRK3,
never decompressing) — and reports the compression ratio, the flop-count
frame of the deck's roofline argument, and the L2 agreement.

Run: python examples/demo_tt.py [N] [rank]
"""

import os
import sys
import time

import numpy as np

import jax

# TT-SVD in float32 truncates meaningfully at rank ~20; the demo's
# accuracy story needs f64 (set via config: this image's sitecustomize
# initializes JAX before env vars are read).  The TT layer runs eagerly
# (many small host-driven ops), so pin CPU — a remote accelerator would
# pay a round-trip per op.
jax.config.update("jax_enable_x64", True)
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from jaxstream.tt.solver import (
    KroneckerOperator,
    diff2_periodic,
    make_tt_stepper,
)
from jaxstream.tt.tensor_train import tt_decompose, tt_reconstruct


def main(n: int = 128, rank: int = 16):
    kappa = 1.0e-3
    dx = 1.0 / n
    dt = 0.2 * dx * dx / kappa
    nsteps = 100

    x = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(x, x, indexing="ij")
    q0 = (np.exp(-((X - 0.3) ** 2 + (Y - 0.4) ** 2) / 0.005)
          + 0.5 * np.sin(2 * np.pi * X) * np.sin(4 * np.pi * Y) ** 2)
    q0 = jnp.asarray(q0, jnp.float64)

    # Dense oracle: q' = kappa (Dxx + Dyy) q via matmuls.
    D = kappa * diff2_periodic(n, dx)

    @jax.jit
    def dense_step(q):
        def rhs(v):
            return D @ v + v @ D.T
        k1 = rhs(q)
        y1 = q + dt * k1
        y2 = 0.75 * q + 0.25 * (y1 + dt * rhs(y1))
        return q / 3.0 + 2.0 / 3.0 * (y2 + dt * rhs(y2))

    qd = q0
    t0 = time.perf_counter()
    for _ in range(nsteps):
        qd = dense_step(qd)
    qd.block_until_ready()
    t_dense = time.perf_counter() - t0

    # TT path: same operator as a Kronecker sum, evolved on the cores.
    op = KroneckerOperator([(0, D), (1, D)])
    qt = tt_decompose(q0, max_rank=rank)
    step = make_tt_stepper(op, dt, max_rank=rank)
    t0 = time.perf_counter()
    for _ in range(nsteps):
        qt = step(qt)
    jax.block_until_ready(qt.cores)
    t_tt = time.perf_counter() - t0

    qr = tt_reconstruct(qt)
    err = float(jnp.linalg.norm(qr - qd) / jnp.linalg.norm(qd))
    dense_params = n * n
    tt_params = sum(int(np.prod(c.shape)) for c in qt.cores)
    print(f"N={n} rank<={rank}  steps={nsteps}")
    print(f"compression: {dense_params} -> {tt_params} parameters "
          f"({dense_params / tt_params:.1f}x)")
    print(f"L2 relative error vs dense: {err:.2e}")
    print(f"wall: dense {t_dense:.2f}s, TT {t_tt:.2f}s (unfused small ops; "
          f"the deck's flop argument is the asymptotic story, p.19)")
    assert err < 1e-3, err


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    r = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    main(n, r)
