"""Cosine-bell advection demo — Williamson TC1 (reference deck p.13/p.18).

One full 12-day revolution of the bell around the sphere, flow tilted 45
degrees so it crosses panel edges and corners; prints peak retention, mass
conservation, and error norms.
"""

import sys
import time

import numpy as np
import jax

sys.path.insert(0, ".")

from jaxstream.config import EARTH_RADIUS
from jaxstream.geometry.cubed_sphere import build_grid
from jaxstream.models.advection import TracerAdvection
from jaxstream.physics.initial_conditions import cosine_bell, solid_body_wind
from jaxstream.utils.diagnostics import error_norms, total_mass


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    scheme = sys.argv[2] if len(sys.argv) > 2 else "ppm"
    halo = 3 if scheme == "ppm" else 2
    grid = build_grid(n, halo=halo, radius=EARTH_RADIUS)
    u0 = 2 * np.pi * EARTH_RADIUS / (12 * 86400)
    wind = solid_body_wind(grid, u0, alpha_rot=np.pi / 4)
    model = TracerAdvection(grid, wind, scheme=scheme)
    state = model.initial_state(cosine_bell(grid))
    q0 = state["q"]
    m0 = float(total_mass(grid, q0))

    dt = 0.35 * grid.radius * grid.dalpha / u0
    nsteps = int(12 * 86400 / dt)
    print(f"TC1 C{n} {scheme}: dt={dt:.0f}s, {nsteps} steps (12 days, one "
          f"revolution) on {jax.devices()[0].platform}")
    wall = time.time()
    state, t = model.run(state, nsteps, dt)
    jax.block_until_ready(state)
    wall = time.time() - wall

    q = state["q"]
    m1 = float(total_mass(grid, q))
    err = {k: float(v) for k, v in error_norms(grid, q, q0).items()}
    print(f"wall {wall:.1f}s ({nsteps / wall:.0f} steps/s)")
    print(f"peak: {float(q.max()):.1f} K of 1000 (deck demo: 999.5 at day 0)")
    print(f"min: {float(q.min()):.2f} K, mass drift {(m1 - m0) / m0:.2e}")
    print(f"error norms after one revolution: {err}")


if __name__ == "__main__":
    main()
