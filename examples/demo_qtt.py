"""QTT operator numerics: a 2-D diffusion solve whose cost is O(log N).

The order-d form of the deck's TT thesis (p.3/5/19): the (N, N) field
lives as base-4 digit cores (O(log N) parameters for smooth fields), the
5-point Laplacian is an exact bond-9 TT-matrix over the digit chain, and
each SSPRK3 stage is one TT-matvec + one fixed-rank rounding — all under
``jax.jit`` with static shapes.  At N = 65536 the dense field would be
34 GB; the QTT state is a few thousand parameters and the step takes
~0.1 s on one CPU core (measured table in docs/DESIGN.md).

Run: python examples/demo_qtt.py [N] [rank] [steps]
     (defaults 4096, 12, 10; N must be a power of 4; above 4096 the
      initial state is built separably — the dense field never exists)
"""

import os
import sys
import time

import numpy as np

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# CPU f64: the demo is a scaling measurement, and f64 keeps the
# accuracy story clean (f32 runs use the masked-Gram rounding path).
jax.config.update("jax_enable_x64", True)
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import jax.numpy as jnp

from jaxstream.tt.qtt import (
    make_qtt_diffusion_stepper,
    qtt_compress,
    qtt_compress_separable,
    qtt_decompress,
)


def main():
    args = sys.argv[1:]
    N = int(args[0]) if len(args) > 0 else 4096
    rank = int(args[1]) if len(args) > 1 else 12
    steps = int(args[2]) if len(args) > 2 else 10
    k = N.bit_length() - 1
    if N <= 0 or N != 4 ** (k // 2):
        sys.exit(f"N={N} must be a power of 4 (e.g. 256, 1024, 4096, "
                 "16384, 65536)")
    x = np.arange(N) / N
    rows = np.stack([np.sin(2 * np.pi * x), np.cos(2 * np.pi * x)])
    cols = np.stack([np.cos(4 * np.pi * x), np.ones(N)])

    dx = 1.0 / N
    dt = 0.1 * dx * dx
    t0 = time.perf_counter()
    if N <= 4096:
        q0 = sum(np.outer(rows[k], cols[k]) for k in range(2))
        y = qtt_compress(q0, rank)
    else:
        y = qtt_compress_separable(rows, cols, rank)
    n_params = sum(int(np.prod(c.shape)) for c in y)
    print(f"N={N}: state {n_params} params vs {N * N} dense cells "
          f"({N * N / n_params:.0f}:1), prep {time.perf_counter() - t0:.2f}s")

    step = jax.jit(make_qtt_diffusion_stepper(N, 1.0, dx, dt, rank))
    y = [jnp.asarray(c) for c in y]
    out = step(y)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        y = step(y)
    jax.block_until_ready(y)
    per = (time.perf_counter() - t0) / steps
    print(f"QTT SSPRK3 diffusion: {per * 1e3:.2f} ms/step "
          f"({steps} steps; cost is ~log N — see DESIGN.md table)")
    if N <= 4096:
        q1 = np.asarray(qtt_decompress([np.asarray(c) for c in y]))
        print(f"field range after {steps} steps: "
              f"[{q1.min():.4f}, {q1.max():.4f}] (finite: "
              f"{np.isfinite(q1).all()})")


if __name__ == "__main__":
    main()
