"""Headline benchmark: simulated-days/sec/chip, Williamson TC5 at C384.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline (BASELINE.json north star): >=1000 simulated-days/sec on a
v5p-256 pod => 1000/256 = 3.90625 sim-days/sec/chip. ``vs_baseline`` is
our per-chip rate divided by that.  Acceptance gates run first (stderr
only) and force value 0 on any breach: TC2 C48 5-day l1/l2/linf height
errors + mass conservation, and TC5 C96 15-day stability (finite,
physical h range, mass conservation) — thresholds justified against the
measured f64 truncation of this discretization (see accuracy_gates).
The timed C384 run itself is additionally gated (finite, physical h
range, mass drift < 1e-3 over its own ~26 simulated days).

The timed step is dt=75 s — matched to the worst-cell CFL the C96 gate
config has always run at; the verification evidence (15-day stability,
temporal error at the f32 roundoff floor) is in ``bench_tc5``'s
docstring and DESIGN.md "The time step".  The ``variants`` JSON field
records the mixed16-carry rate (h int16 + u bf16, default gate band),
the dt=90 empirical-max-stable rate (own 15-day gate each run), and
the Galewsky-nu4 rate (day-6 physics gate); the dt=60-equivalent rate
is a top-level field.  The ``ensemble`` field reports the batched
perturbed-IC ensemble section (``bench_ensemble``, TC5 C96 at the
CFL-matched dt=300 — the members-x-moderate-resolution regime where
batching pays): aggregate sim-days/sec/chip at B in {1, 4, 16} with
B-scaled rooflines and the B=1 bitwise acceptance check.  The ``io``
field (round 9) reports the async-host-pipeline section
(``bench_io``): steps/s with history+checkpoint+telemetry on, async
vs sync, against the io-off baseline, plus the per-mode
``host_wait_s`` totals from the runs' own telemetry.  The ``serving``
field (round 11) reports the continuous-batching ensemble server
section (``bench_serving``): packed heterogeneous-run-length traffic
vs serial B=1 aggregate sim-days/sec/chip, slot occupancy, request
latency p50/p99, warmup compile count and the zero-steady-state-
recompile check, plus the >= 0.9x floor vs the static-B=16 ensemble
rate.  The ``serving_multichip`` field (round 12,
``bench_serving_multichip``) measures one server process driving a
whole device mesh through ``serve.placement``: aggregate
member-steps/s at equal per-chip batch vs the single-device packed
rate, with the >= 0.8x-of-ideal N-chip scaling floor enforced on real
accelerators (reported-only on fake CPU meshes), the
single-vs-multichip packed-h byte-parity check, and zero steady-state
recompiles per placement mode.  The ``serving_slo`` field (round 14,
``bench_serving_slo``) replays a deterministic heavy-tailed mixed-IC
trace through the asyncio HTTP gateway over loopback with live
autoscaling and enforces the SLO floors: request p50/p99 latency,
goodput >= 0.5x the packed serving rate, completed + typed-shed ==
submitted, >= 1 autoscale resize, zero steady-state recompiles after
the resize.  The ``perf`` field (round 19, ``bench_perf``) is the performance
observatory's section — hardware identity, a full cost stamp of the
bench stepper (XLA memory_analysis footprint bytes, compile seconds,
the flops-vs-analytic cross-check on XLA-visible rungs) and a live
device-memory snapshot — and the ``perf_ledger`` field
(``bench_perf_ledger``) gates this run against the recorded
``BENCH_r*.json`` trajectory (enforced on accelerators, reported-only
for CPU smoke; ``scripts/perf_ledger.py`` renders/checks the same
history offline).
``python bench.py --smoke`` runs the C24 bitrot canary instead (no gates;
wired into tier-1 via tests/test_bench_smoke.py); ``python bench.py
--compile-report`` prints cold-vs-warm compile seconds for the
``JAXSTREAM_COMPILE_CACHE`` persistent-cache opt-in; ``python bench.py
--precision-report`` prints the round-10 precision ladder (f32 /
bf16-stage / mixed16-carry / stacked measured side by side at C384
with precision-corrected rooflines — ``bench_precision_report``).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_PER_CHIP = 1000.0 / 256.0  # sim-days/sec/chip
BENCH_DT = 75.0  # timed step (s); CFL-matched, see bench_tc5 docstring


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _device_count() -> int:
    """In-process device count (1 when jax is unavailable/broken)."""
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def _platform() -> str:
    """Device platform id ('unknown' when jax is unavailable) — the
    hardware tag the perf ledger classes trajectory points by."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "unknown"


def _argv_value(flag: str) -> str:
    """Value following ``flag`` in argv, or '' (no argparse: the JSON
    contract is one stdout line and the flag surface is tiny)."""
    argv = sys.argv[1:]
    if flag in argv:
        i = argv.index(flag)
        if i + 1 < len(argv):
            return argv[i + 1]
    return ""


def _open_telemetry(path: str):
    """Structured-sink handle for ``--telemetry PATH`` (jaxstream.obs).

    The benchmark's rates land as schema-valid ``bench`` records in the
    same JSONL format Simulation emits, so scripts/telemetry_report.py
    reads either.  Never fails the benchmark — a sink problem logs to
    stderr and returns None.
    """
    if not path:
        return None
    try:
        from jaxstream.obs.sink import TelemetrySink, run_manifest

        return TelemetrySink(path, run_manifest(
            config={"harness": "bench.py", "argv": sys.argv[1:]}))
    except Exception as e:
        log(f"bench: telemetry sink unavailable ({type(e).__name__}: {e})")
        return None


def _roofline_json(steps_per_sec: float, n: int, scale: float = 1.0,
                   bytes_scale: float = 1.0, ensemble: int = 1,
                   carry_bytes: int = None, nu4: str = None,
                   precision: str = None):
    """Roofline numbers for one covariant-fused-stepper rate, as JSON.

    The analytic kernel count against the VPU roof (Pallas custom calls
    are invisible to XLA's cost model — see bench_tc5's roofline note);
    ``scale`` adjusts flops AND bytes for non-covariant rungs, while
    ``bytes_scale`` adjusts bytes alone (kept for ad-hoc callers).
    ``ensemble = B``: ``steps_per_sec`` counts BATCHED ensemble steps
    (each advancing all B members) and the analytic cost scales flops
    AND bytes by B together — intensity unchanged — so ensemble
    variants report truthful throughput instead of a B-inflated AI
    (jaxstream.utils.profiling.analytic_cov_step_cost's ensemble note).

    Round-10 accounting satellite — the three precision-aware knobs
    thread straight into ``analytic_cov_step_cost``:

    * ``carry_bytes=2``: 16-bit carry encodings.  Replaces the old
      coarse ``bytes_scale=0.5``, which billed the orography re-read at
      2 bytes too and so OVERSTATED both the byte savings and the AI of
      the 16-bit-carry variants; the reported ``ai`` is now the
      corrected one.
    * ``nu4='split'|'refused'``: del^4-filter variants get the
      re-derived 210 flops/cell/step filter count plus the per-placement
      byte traffic (6 extra f32 field passes split, 3 re-fused) instead
      of the old flops-AND-bytes ``scale=4/3``.
    * ``precision='bf16'``: the stage-policy variants additionally
      report ``bf16_flop_fraction`` and their percentage of the
      harmonic-blend ``mixed_vpu_roof`` (bf16 ops pack 2x per VPU
      lane); ``pct_of_compute_roof`` stays the f32 roof so rows remain
      comparable across variants.

    Round 19: the arithmetic itself moved to
    ``jaxstream.obs.perf.roofline_json`` — the ONE definition of cost
    accounting the probe CLIs and the serving cost stamps share; this
    wrapper keeps bench's never-fail-a-variant contract.  Returns None
    when the profiling helpers are unavailable.
    """
    try:
        from jaxstream.obs.perf import roofline_json

        return roofline_json(steps_per_sec, n, scale=scale,
                             bytes_scale=bytes_scale,
                             ensemble=ensemble,
                             carry_bytes=carry_bytes, nu4=nu4,
                             precision=precision)
    except Exception as e:
        log(f"bench: variant roofline unavailable ({e})")
        return None


def _variant_entry(sim_days_per_sec: float, steps_per_sec: float, n: int,
                   scale: float = 1.0, bytes_scale: float = 1.0,
                   ensemble: int = 1, carry_bytes: int = None,
                   nu4: str = None, precision: str = None, **extra):
    """One ``variants`` JSON entry: rate + its own roofline numbers
    (round-6 satellite: the roofline is reported per variant, not just
    for the headline run).  ``scale`` adjusts the analytic covariant
    step cost for variants whose step does more work; ``carry_bytes``/
    ``nu4``/``precision`` are the precision-aware accounting knobs
    (see :func:`_roofline_json`); ``ensemble=B`` marks
    ``steps_per_sec`` as batched B-member steps (the roofline bills B
    members of flops AND bytes per step — truthful intensity) and
    ``sim_days_per_sec`` as AGGREGATE across members.  Every entry
    carries its ``dt60_equivalent`` (steps/s x 60 s) so cross-round
    rate comparisons never depend on the variant's own dt."""
    e = {"sim_days_per_sec": round(sim_days_per_sec, 4),
         "steps_per_sec": round(steps_per_sec, 2),
         "vs_baseline": round(sim_days_per_sec / BASELINE_PER_CHIP, 4),
         "dt60_equivalent": round(
             steps_per_sec * ensemble * 60.0 / 86400.0, 4)}
    if ensemble > 1:
        e["members"] = ensemble
        e["member_steps_per_sec"] = round(steps_per_sec * ensemble, 2)
    rl = _roofline_json(steps_per_sec, n, scale, bytes_scale, ensemble,
                        carry_bytes=carry_bytes, nu4=nu4,
                        precision=precision)
    if rl is not None:
        e["roofline"] = rl
    e.update(extra)
    return e


def _run_case(n, case, days, dt):
    """Integrate a Williamson case with the covariant formulation — the
    same discretization the benchmark times (fused Pallas stepper when it
    compiles, classic jnp otherwise).  Returns (grid, h0, h1) interior
    height fields as f64 numpy."""
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import (williamson_tc2,
                                                      williamson_tc5)
    from jaxstream.stepping import integrate

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    if case == "tc2":
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        b_ext = None
    else:
        h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY,
                                             EARTH_OMEGA)
    nsteps = int(days * 86400 / dt)
    try:
        model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                      omega=EARTH_OMEGA, b_ext=b_ext,
                                      backend="pallas")
        step = model.make_fused_step(dt)
        state = model.initial_state(h_ext, v_ext)
        y = model.compact_state(state)
        run = jax.jit(lambda y: integrate(step, y, 0.0, nsteps, dt))
        out, _ = run(y)
        jax.block_until_ready(out["h"])
    except Exception as e:
        log(f"gate: fused stepper unavailable ({type(e).__name__}); "
            "using classic path")
        model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                      omega=EARTH_OMEGA, b_ext=b_ext)
        state = model.initial_state(h_ext, v_ext)
        out, _ = model.run(state, nsteps, dt)
    return (grid, np.asarray(state["h"], np.float64),
            np.asarray(out["h"], np.float64))


def accuracy_gates():
    """The Williamson-suite acceptance gates, at the standard the repo
    cites (SURVEY.md §4; BASELINE.md "L2 parity" row).  Thresholds are
    the measured f64-CPU truncation values of THIS discretization with
    a ~2x margin (the f32-TPU fused path reproduces them to 3-4
    digits; DESIGN.md "Acceptance gates"):

      TC2 C48, 5 days, dt=600 — the CFL-MATCHED step to the timed
      C384/dt=75 configuration (dt scales with n: 75 x 384/48), so
      this gate ties the benchmark's own Courant number to an
      ANALYTIC-truth error norm (TC2's steady state), round-5 VERDICT
      ask #5.  dt=600 vs dt=300 error norms agree to 3 digits in both
      f64 and f32 (spatial truncation dominates; SSPRK3 temporal error
      invisible at either step — f64-CPU dt=600: l1 9.93e-4,
      l2 1.372e-3, linf 7.20e-3; f32-TPU fused dt=600: 9.89e-4 /
      1.3716e-3 / 7.22e-3, dt=300: 9.86e-4 / 1.3713e-3 / 7.23e-3),
      and halving the steps halves the gate's wall time (VERDICT
      ask #7):
        l1 < 2e-3, l2 < 2.5e-3, linf < 1.4e-2
        mass drift < 2e-4   (measured f32 1.9e-5 over 720 steps;
                             f64 conserves to roundoff)
      TC5 C96, 15 days, dt=300 i.e. 4 320 steps — CFL-matched to the
      timed config by the same scaling (75 x 384/96 = 300; measured at
      this exact config on the v5e: h in [3 727, 5 953] m from initial
      [3 777, 5 960]; mass drift 1.04e-4):
        all finite, 3 000 < h < 6 500 m, mass drift < 1e-3

    Returns True iff every gate holds (each result logged to stderr).
    """
    ok = True

    grid, h0, h1 = _run_case(48, "tc2", days=5.0, dt=600.0)
    area = np.asarray(grid.interior(grid.area), np.float64)
    dh = h1 - h0
    l1 = np.sum(area * np.abs(dh)) / np.sum(area * np.abs(h0))
    l2 = np.sqrt(np.sum(area * dh**2) / np.sum(area * h0**2))
    linf = np.max(np.abs(dh)) / np.max(np.abs(h0))
    mass = abs(np.sum(area * h1) - np.sum(area * h0)) / np.sum(area * h0)
    log(f"gate TC2 C48 5d dt=600 (CFL-matched to the timed C384 "
        f"dt=75): l1={l1:.3e} (<2e-3) l2={l2:.3e} (<2.5e-3) "
        f"linf={linf:.3e} (<1.4e-2) mass_drift={mass:.3e} (<2e-4)")
    if not (l1 < 2e-3 and l2 < 2.5e-3 and linf < 1.4e-2 and mass < 2e-4):
        log("gate TC2: FAILED")
        ok = False

    grid5, h0, h1 = _run_case(96, "tc5", days=15.0, dt=300.0)
    area5 = np.asarray(grid5.interior(grid5.area), np.float64)
    finite = bool(np.all(np.isfinite(h1)))
    mass5 = (abs(np.sum(area5 * h1) - np.sum(area5 * h0))
             / np.sum(area5 * h0))
    log(f"gate TC5 C96 15d: finite={finite} "
        f"h_range=[{h1.min():.0f},{h1.max():.0f}] (in (3000,6500)) "
        f"mass_drift={mass5:.3e} (<1e-3)")
    if not (finite and h1.min() > 3000.0 and h1.max() < 6500.0
            and mass5 < 1e-3):
        log("gate TC5: FAILED")
        ok = False
    return ok


def bench_tc5(n=384, dt=BENCH_DT, warm_steps=10, timed_steps=24000,
              with_variants=True):
    """Timed run at dt=75 s — the CFL-matched time step (round 4).

    dt was 60 s through round 3; that configuration ran the C384 grid at
    a worst-cell 2-D CFL of 1.45 while this benchmark's own TC5 C96
    acceptance gate (dt=300, 15 days, re-proven stable on every bench
    run) runs the same discretization at 1.81.  dt=75 puts C384 at the
    gate's own CFL (1.816 vs 1.810, computed per-cell from
    sqrt(g h) + |v| and the metric cell spacings).  Verified on the v5e
    before adoption (round-4 evidence, DESIGN.md "The time step"):

    * 15-day C384 TC5 run at dt=75: finite, h in [3681, 5956] m, mass
      drift 4.1e-4 (dt=60: [3682, 5957], 5.2e-4).
    * Temporal accuracy: day-1 h l2-difference vs a dt=15 reference is
      1.15e-4 (dt=75) vs 1.09e-4 (dt=60) — flat in dt, i.e. BOTH are at
      the f32 roundoff floor; the SSPRK3 dt^3 truncation is invisible
      at either step.  At day 15 the difference vs a dt=30 reference is
      6.7e-4 vs 5.7e-4 with ratio 1.19 where pure time truncation would
      give (75/60)^3 = 1.95 — trajectory decorrelation, not scheme
      error, dominates both.
    * The timed windows below integrate ~26 simulated days of TC5 and
      the final state is gated (finite, physical h range, mass drift
      < 1e-3) every run — the dt=75 claim re-proves itself.

    The metric (sim-days/sec/chip) is dt-aware by construction: a
    larger stable-and-accurate step is a legitimate solver property,
    the same axis on which implicit/semi-Lagrangian dynamical cores
    compete.  The dt=60 equivalent is still printed each run for
    cross-round comparability.
    """
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water import ShallowWater
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc5
    from jaxstream.stepping import integrate

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)

    # Fastest-first ladder, probing one real step of each candidate so a
    # Mosaic compile failure (VMEM/shape limits, CPU bench runs) falls
    # through instead of crashing:
    #   1. covariant fused stepper (3 fields, rotation strips; ~1.4x the
    #      Cartesian fused stepper at C384),
    #   2. Cartesian fused stepper (in-kernel exchange),
    #   3. classic jnp SSPRK3.
    state = step = None
    rung = None
    try:
        model = CovariantShallowWater(
            grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
            backend="pallas")
        step = model.make_fused_step(dt)
        y = model.compact_state(model.initial_state(h_ext, v_ext))
        jax.block_until_ready(jax.jit(step)(y, jnp.float32(0.0)))
        state = y
        rung = "cov_fused"
        log("bench: using covariant compact fused SSPRK3 stepper "
            "(interior-only carry, rotation strips)")
    except Exception as e:
        log(f"bench: covariant fused stepper unavailable "
            f"({type(e).__name__}: {e})")
    if state is None:
        try:
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext,
                                 backend="pallas")
            step = model.make_fused_step(dt, in_kernel_exchange=True)
            y = model.extend_state(model.initial_state(h_ext, v_ext),
                                   with_strips=True)
            jax.block_until_ready(jax.jit(step)(y, jnp.float32(0.0)))
            state = y
            rung = "cart_fused"
            log("bench: using Cartesian fused SSPRK3 stepper "
                "(in-kernel exchange)")
        except Exception as e:
            log(f"bench: Cartesian fused stepper unavailable "
                f"({type(e).__name__}: {e})")
    if state is None:
        # Classic stepper; plain Pallas RHS kernel if it compiles (the
        # fused stage kernels have stricter VMEM/shape needs), jnp last.
        try:
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext,
                                 backend="pallas")
            state = model.initial_state(h_ext, v_ext)
            jax.block_until_ready(model.rhs(state, 0.0)["h"])
            rung = "pallas_rhs"
            log("bench: using classic stepper with pallas RHS kernel")
        except Exception as e:
            log(f"bench: pallas RHS unavailable ({type(e).__name__}); "
                f"using jnp")
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext)
            state = model.initial_state(h_ext, v_ext)
        step = model.make_step(dt, "ssprk3")

    # One compiled executable for any step count: nsteps rides the carry as
    # a traced bound (fori_loop lowers to a while), so the timed region is
    # pure device execution — no recompile between warmup and timing (the
    # reference's "no recompilation during timestepping" invariant, deck
    # p.10, applied to the benchmark harness itself).
    run = jax.jit(
        lambda y, nsteps: integrate(step, y, 0.0, nsteps, dt), donate_argnums=0
    )

    t0 = time.perf_counter()
    state_w, _ = run(state, warm_steps)
    jax.block_until_ready(state_w)
    log(f"bench: warmup {warm_steps} steps (incl. compile) "
        f"{time.perf_counter() - t0:.1f}s on {jax.devices()[0].platform}")

    from jaxstream.utils.profiling import steady_state_rate

    # steady_state_rate wants run(y, k) -> y; adapt integrate's (y, t).
    k1 = timed_steps // 4
    steps_per_sec, out = steady_state_rate(
        lambda y, k: run(y, k)[0], state_w, k1=k1, k2=timed_steps)

    # The timed window doubles as a >15-simulated-day stability gate on
    # the exact benchmarked configuration (this is what re-proves the
    # CFL-matched dt every run — see the docstring).  The carry's h is
    # extended on the cart_fused rung — gate on the interior either way.
    area_w = np.asarray(grid.interior(grid.area), np.float64)
    h0_f64 = np.asarray(grid.interior(h_ext), np.float64)
    mass0 = np.sum(area_w * h0_f64)

    def tc5_gate(h, label, mass_tol=1e-3):
        """Shared TC5 C384 stability gate: finite, physical h range,
        mass conserved vs the initial state.  Returns ok (logged).
        ``mass_tol``: every current caller uses the 1e-3 default
        (round 5 — the mixed16 carry holds mass at the default band);
        the override is kept for ad-hoc variants with a documented
        wider band, as the demoted bf16 line had."""
        if h.shape[-1] != grid.n:
            h = grid.interior(h)
        h = np.asarray(h, np.float64)
        finite = bool(np.all(np.isfinite(h)))
        mass_drift = abs(np.sum(area_w * h) - mass0) / mass0
        ok = (finite and 3000.0 < h.min() and h.max() < 6500.0
              and mass_drift < mass_tol)
        log(f"bench gate C{n} TC5 {label}: finite={finite} "
            f"h_range=[{h.min():.0f},{h.max():.0f}] (in (3000,6500)) "
            f"mass_drift={mass_drift:.3e} (<{mass_tol:g})")
        return ok

    # Total integration reaching `out`: warmup + both measurement
    # windows (k1 then timed_steps; retries would add more).
    sim_days_run = (warm_steps + k1 + timed_steps) * dt / 86400.0
    if not tc5_gate(out["h"], f"{sim_days_run:.1f}d (the timed run)"):
        raise RuntimeError(f"bench timed-run gate breached at dt={dt}")
    sim_days_per_sec = steps_per_sec * dt / 86400.0
    log(f"bench: C{n} TC5 windows {k1}/{timed_steps} steps -> "
        f"{steps_per_sec:.1f} steps/s (dt={dt}s, dispatch-overhead-free "
        "two-window differencing, utils.profiling.steady_state_rate)")
    log(f"bench: dt=60 equivalent (round-1..3 comparable): "
        f"{steps_per_sec * 60.0 / 86400.0:.4f} sim-days/sec/chip")
    try:  # roofline context (deck p.19's analysis frame; best-effort)
        from jaxstream.utils.profiling import (
            TPU_V5E, TPU_V5E_VPU, Roofline, analytic_cov_step_cost,
            roofline)

        if rung in ("cov_fused", "cart_fused", "pallas_rhs"):
            # These rungs' math lives in Pallas kernels — invisible to
            # XLA's cost_analysis (round 1 printed a ~200x-off roofline
            # that way).  Use the hand-counted static-stencil cost
            # against the VPU roof (the stencils never touch the MXU);
            # consistent with DESIGN.md's stage-kernel bisection.  The
            # Cartesian-formulation rungs carry 4 fields + 3-vector
            # algebra: ~1.4x the covariant flops (DESIGN.md throughput
            # ladder) — scale the count and say so.
            scale = 1.0 if rung == "cov_fused" else 1.4
            c = analytic_cov_step_cost(n)
            r = Roofline(c["flops"] * scale, c["bytes"] * scale,
                         1.0 / steps_per_sec, TPU_V5E_VPU)
            tag = ("" if rung == "cov_fused"
                   else f" (x{scale} Cartesian-formulation estimate)")
            log("bench: analytic kernel count "
                f"({c['flops_per_cell_stage']:.0f} flops/cell/stage, "
                f"+-15%{tag}; XLA cost_analysis excludes Pallas custom "
                "calls) " + r.report())
        else:  # pure-jnp rung: XLA sees every op, cost_analysis is real
            r = roofline(jax.jit(step), out, jnp.float32(0.0),
                         seconds=1.0 / steps_per_sec, roof=TPU_V5E)
            log("bench: XLA-cost_analysis roofline " + r.report())
    except Exception as e:
        log(f"bench: roofline unavailable ({e})")

    variants = {"dt60_equivalent": round(steps_per_sec * 60.0 / 86400.0, 4)}
    if with_variants and rung == "cov_fused":
        # mixed16-carry variant (round 5; replaces the DEMOTED bf16
        # line): h stored int16 fixed-point (1/16 m quanta about a
        # static offset, magic-constant rounding) + u stored bf16.
        # Mass lives entirely in h, so this keeps the bf16 encoding's
        # u-side DMA speed (+5.4% of the +6.8%) while holding mass at
        # the int16 level — measured 4.8e-5 over 10.4 days at C384
        # (bf16 h-anomaly leaked 1.3e-3/day and needed its own 3e-2
        # band, the round-4 weakness).  Gate band here: THE DEFAULT
        # 1e-3.  Remaining trade: u's bf16 ulp puts TC2 C48 5-day l2
        # at 2.2e-3 vs f32's 1.37e-3 (passes the 2.5e-3 gate;
        # DESIGN.md carry ladder).
        try:
            from jaxstream.ops.pallas.precision import mixed16_encoding

            st0 = model.initial_state(h_ext, v_ext)
            cd, off, hs = mixed16_encoding(st0["h"])
            step16 = model.make_fused_step(dt, carry_dtype=cd,
                                           h_offset=off, h_scale=hs)
            y16 = model.encode_carry(model.compact_state(st0), cd, off,
                                     hs)
            run16 = jax.jit(
                lambda y, k: integrate(step16, y, 0.0, k, dt)[0],
                donate_argnums=0)
            y16 = run16(y16, warm_steps)
            jax.block_until_ready(y16["h"])
            rate16, out16 = steady_state_rate(
                lambda y, k: run16(y, k), y16, k1=3000, k2=12000)
            h16 = model.decode_carry(out16, h_offset=off, h_scale=hs)["h"]
            if not tc5_gate(h16, "mixed16 timed run"):
                raise RuntimeError("mixed16 variant gate breached")
            v16 = rate16 * dt / 86400.0
            # carry_bytes=2: the h int16 + u bf16 carry halves the
            # carry field-pass DMA; the orography re-read stays f32
            # (the old bytes_scale=0.5 billed b at 2 bytes too,
            # overstating the variant's AI — round-10 accounting
            # satellite, analytic_cov_step_cost's carry_bytes note).
            variants["mixed16_carry"] = _variant_entry(
                v16, rate16, n, carry_bytes=2, dt=dt)
            log(f"bench variant mixed16-carry: {rate16:.1f} steps/s -> "
                f"{v16:.4f} sim-days/sec/chip "
                f"({v16 / BASELINE_PER_CHIP:.4f}x baseline; h int16 + "
                "u bf16, mass at default band; DESIGN.md carry ladder)")
        except Exception as e:
            log(f"bench variant mixed16-carry unavailable "
                f"({type(e).__name__}: {e})")
        # bf16-stage variant (round 10): reduced precision IN the stage
        # arithmetic — flux face-average velocities, PLR limiter
        # algebra and router rotations in bfloat16, every accumulator
        # and metric term f32, bf16 inter-stage strips
        # (jaxstream.ops.pallas.precision; measured error budgets in
        # tests/test_precision.py and DESIGN.md "Precision ladder").
        # Own 15-day TC5 gate at the DEFAULT mass band: warm + 3000 +
        # 14400 steps at dt=75 integrates 15.1 simulated days, so the
        # timed windows ARE the gate integration.
        try:
            from jaxstream.ops.pallas.precision import encode_strips

            stepbf = model.make_fused_step(dt, precision="bf16")
            ybf = encode_strips(
                model.compact_state(model.initial_state(h_ext, v_ext)),
                "bf16")
            runbf = jax.jit(
                lambda y, k: integrate(stepbf, y, 0.0, k, dt)[0],
                donate_argnums=0)
            ybf = runbf(ybf, warm_steps)
            jax.block_until_ready(ybf["h"])
            ratebf, outbf = steady_state_rate(
                lambda y, k: runbf(y, k), ybf, k1=3000, k2=14400)
            if not tc5_gate(outbf["h"], "15.1d bf16-stage timed run"):
                raise RuntimeError("bf16-stage variant gate breached")
            vbf = ratebf * dt / 86400.0
            variants["bf16_stage"] = _variant_entry(
                vbf, ratebf, n, precision="bf16", dt=dt)
            log(f"bench variant bf16-stage: {ratebf:.1f} steps/s -> "
                f"{vbf:.4f} sim-days/sec/chip "
                f"({vbf / BASELINE_PER_CHIP:.4f}x baseline; bf16 "
                "flux/recon/router arithmetic, f32 accumulators + "
                "metric terms, own 15-day gate at the default band)")
        except Exception as e:
            log(f"bench variant bf16-stage unavailable "
                f"({type(e).__name__}: {e})")
        # dt=90 variant: the empirical max-stable step (round 4: 15-day
        # stable at dt=90 and 82.5; NaN at 100/110/120, so ~10% below
        # the blowup edge — too thin a margin for the default, which
        # stays at the CFL-matched 75).  Day-1 temporal error at dt=90
        # is 1.20e-4 vs a dt=15 reference — same roundoff-floor
        # plateau as dt=60/75, so accuracy is unchanged.  steps/s is
        # dt-independent (dt is a kernel constant), so the rate below
        # reuses the timed measurement; the 15-day stability gate is
        # re-proven here on every bench run.
        try:
            step90 = model.make_fused_step(90.0)
            y90 = model.compact_state(model.initial_state(h_ext, v_ext))
            run90 = jax.jit(
                lambda y, k: integrate(step90, y, 0.0, k, 90.0)[0],
                donate_argnums=0)
            h90 = run90(y90, 14400)["h"]
            if tc5_gate(h90, "15d at dt=90"):
                v90 = steps_per_sec * 90.0 / 86400.0
                variants["dt90_max_stable"] = _variant_entry(
                    v90, steps_per_sec, n, dt=90.0)
                log(f"bench variant dt90-max-stable: {v90:.4f} "
                    f"sim-days/sec/chip ({v90 / BASELINE_PER_CHIP:.4f}x"
                    " baseline; empirical stability edge ~dt=100, "
                    "margin rationale in DESIGN.md)")
            else:
                log("bench variant dt90: stability gate FAILED — "
                    "not reported")
        except Exception as e:
            log(f"bench variant dt90 unavailable "
                f"({type(e).__name__}: {e})")
        # Combined variant (round 5): the two trades above are
        # orthogonal — mixed16 trades u-ulp accuracy for rate, dt=90
        # trades stability margin for sim-days/step — so their product
        # is a legitimate gated configuration.  Requires BOTH parents'
        # gates green this run, plus its own 15-day integration gate
        # at the default mass band.
        if "mixed16_carry" in variants and "dt90_max_stable" in variants:
            try:
                # st0/off/cd/hs are the mixed16 parent's own values —
                # the guard above proves that block completed, so the
                # combined gate tests EXACTLY the reported encoding.
                s9016 = model.make_fused_step(90.0, carry_dtype=cd,
                                              h_offset=off, h_scale=hs)
                y9016 = model.encode_carry(model.compact_state(st0), cd,
                                           off, hs)
                run9016 = jax.jit(
                    lambda y, k: integrate(s9016, y, 0.0, k, 90.0)[0],
                    donate_argnums=0)
                out9016 = run9016(y9016, 14400)          # 15 days
                h9016 = model.decode_carry(out9016, h_offset=off,
                                           h_scale=hs)["h"]
                if tc5_gate(h9016, "15d at dt=90 + mixed16"):
                    # rate: the mixed16 steps/s (dt-independent) — the
                    # RAW measurement, not the display-rounded JSON
                    # field, so presentation rounding cannot skew it.
                    v = rate16 * 90.0 / 86400.0
                    variants["mixed16_dt90"] = _variant_entry(
                        v, rate16, n, carry_bytes=2, dt=90.0)
                    log(f"bench variant mixed16+dt90: {v:.4f} "
                        f"sim-days/sec/chip "
                        f"({v / BASELINE_PER_CHIP:.4f}x baseline; both "
                        "parent trades documented, own 15-day gate)")
                else:
                    log("bench variant mixed16+dt90: gate FAILED — "
                        "not reported")
            except Exception as e:
                log(f"bench variant mixed16+dt90 unavailable "
                    f"({type(e).__name__}: {e})")
        # Stacked variant (round 10): bf16 stage arithmetic + mixed16
        # carry + dt=90 — ALL three orthogonal trades at once
        # (arithmetic dtype / storage dtype / step size).  Requires all
        # three parents' gates green this run, then its own 15-day
        # integration at the default mass band (the three trades have
        # never been proven jointly stable by their parents — the
        # stacked gate is the evidence).  Its rate is measured on its
        # OWN stepper: it runs arithmetic neither parent runs.
        if ("bf16_stage" in variants and "mixed16_carry" in variants
                and "dt90_max_stable" in variants):
            try:
                from jaxstream.ops.pallas.precision import encode_strips

                sstk = model.make_fused_step(
                    90.0, precision="bf16", carry_dtype=cd,
                    h_offset=off, h_scale=hs)
                ystk = encode_strips(model.encode_carry(
                    model.compact_state(st0), cd, off, hs), "bf16")
                runstk = jax.jit(
                    lambda y, k: integrate(sstk, y, 0.0, k, 90.0)[0],
                    donate_argnums=0)
                outstk = runstk(ystk, 14400)          # 15 days
                hstk = model.decode_carry(outstk, h_offset=off,
                                          h_scale=hs)["h"]
                if tc5_gate(hstk, "15d bf16-stage + mixed16 at dt=90"):
                    ratestk, outstk2 = steady_state_rate(
                        lambda y, k: runstk(y, k), outstk,
                        k1=3000, k2=12000)
                    # The timing windows integrate ~16 MORE days on a
                    # stack never proven stable past its 15-day gate —
                    # re-gate the post-timing state like every sibling
                    # so a late blowup can't publish a rate.
                    hstk2 = model.decode_carry(
                        outstk2, h_offset=off, h_scale=hs)["h"]
                    if not tc5_gate(hstk2, "post-timing stacked (31d)"):
                        raise RuntimeError(
                            "stacked variant breached its gate during "
                            "the timing windows")
                    v = ratestk * 90.0 / 86400.0
                    variants["bf16_mixed16_dt90"] = _variant_entry(
                        v, ratestk, n, carry_bytes=2, precision="bf16",
                        dt=90.0)
                    log(f"bench variant bf16+mixed16+dt90 (stacked): "
                        f"{ratestk:.1f} steps/s -> {v:.4f} "
                        f"sim-days/sec/chip "
                        f"({v / BASELINE_PER_CHIP:.4f}x baseline; "
                        f"dt60-equivalent "
                        f"{ratestk * 60.0 / 86400.0:.4f}; all three "
                        "parent trades gated green this run + own "
                        "15-day gate)")
                else:
                    log("bench variant bf16+mixed16+dt90: gate FAILED "
                        "— not reported")
            except Exception as e:
                log(f"bench variant bf16+mixed16+dt90 unavailable "
                    f"({type(e).__name__}: {e})")
        # temporal_block variant (round 6): k=4 fused SSPRK3 steps per
        # dispatch (make_fused_ssprk3_cov_multistep — bitwise-identical
        # to the headline stepper, so the timed-run gate transfers; its
        # own ~2.8-day window is still gated below).  On one chip this
        # measures dispatch amortization; the exchanges/step and
        # redundant-compute numbers of the multichip deep-halo form
        # (same k) ride along from comm_probe.temporal_block_plan so
        # the JSON line carries the full temporal-blocking story.
        try:
            from jaxstream.utils.comm_probe import temporal_block_plan

            ktb = 4
            steptb = model.make_fused_step(dt, temporal_block=ktb)
            ytb = model.compact_state(model.initial_state(h_ext, v_ext))
            runtb = jax.jit(
                lambda y, kk: integrate(steptb, y, 0.0, kk, dt * ktb)[0],
                donate_argnums=0)
            ytb = runtb(ytb, max(1, warm_steps // ktb))
            jax.block_until_ready(ytb["h"])
            ratetb, outtb = steady_state_rate(
                lambda y, kk: runtb(y, kk), ytb, k1=3000 // ktb,
                k2=12000 // ktb)
            ratetb *= ktb                       # blocks/s -> steps/s
            if not tc5_gate(outtb["h"], f"temporal_block={ktb} timed run"):
                raise RuntimeError("temporal_block variant gate breached")
            vtb = ratetb * dt / 86400.0
            plan = temporal_block_plan(n, 2, ktb)
            variants["temporal_block"] = _variant_entry(
                vtb, ratetb, n, dt=dt, temporal_block=ktb,
                exchanges_per_step=plan["ppermutes_per_step"],
                serialized_exchanges_per_step=plan[
                    "serialized_ppermutes_per_step"],
                redundant_compute_fraction=round(
                    plan["redundant_compute_fraction"], 4))
            log(f"bench variant temporal_block k={ktb}: {ratetb:.1f} "
                f"steps/s -> {vtb:.4f} sim-days/sec/chip "
                f"({vtb / BASELINE_PER_CHIP:.4f}x baseline; "
                f"deep-halo plan: {plan['ppermutes_per_step']:.1f} "
                f"exchanges/step vs 12, redundant compute "
                f"{plan['redundant_compute_fraction']:.3f})")
        except Exception as e:
            log(f"bench variant temporal_block unavailable "
                f"({type(e).__name__}: {e})")
    return sim_days_per_sec, variants


def bench_galewsky(n=384, dt=60.0, nu4=1.0e14, nu4_mode="split"):
    """Galewsky C384 with the del^4 filter stepper — the variant line
    for the flagship validation case.  ``nu4_mode='split'`` is the
    round-5 once-per-step filter kernel (three plain RK stage kernels
    + one filter kernel, 1.90x the round-4 in-stage pair; BASELINE.md
    ladder config #5); ``'refused'`` is the round-10 re-fusion — the
    filter commuted into the stage-1 kernel, 3 kernels + 3 routes per
    step vs split's 4 + 4 (ops/pallas/swe_cov.py re-fusion note).

    Runs the jet to day 6 (8 640 steps) and gates on the instability's
    physics before reporting a rate: finite fields, physical h range,
    mass conservation, day-6 vorticity filaments in the documented band
    (max |zeta| ~1.5e-4 s^-1, docs/galewsky_c384_day6_vorticity.png),
    and a QUIESCENT southern hemisphere (measured 8e-7 vs the north's
    1.5e-4 — any spurious noise source trips this 180x separation).
    The re-fused line runs the IDENTICAL day-6 gate: the two forms'
    trajectories differ by one endpoint filter application (O(damp)),
    so passing the same physics bands is the equivalence evidence.
    dt=60: the jet adds ~80 m/s to the gravity-wave speed, so TC5's
    CFL-matched 75 s does not transfer.  Returns
    ``(sim-days/sec/chip, steps/s)`` — ``(0.0, 0.0)`` on gate breach.
    """
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.ops.fv import vorticity_cov
    from jaxstream.physics.initial_conditions import galewsky
    from jaxstream.stepping import integrate
    from jaxstream.utils.profiling import steady_state_rate

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = galewsky(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA, backend="pallas",
                                  nu4=nu4)
    step = model.make_fused_step(dt, nu4_mode=nu4_mode)
    st = model.initial_state(h_ext, v_ext)
    area = np.asarray(grid.interior(grid.area), np.float64)
    h0 = np.asarray(st["h"], np.float64)
    m0 = np.sum(area * h0)
    run = jax.jit(lambda y, k: integrate(step, y, 0.0, k, dt)[0],
                  donate_argnums=0)

    y = run(model.compact_state(st), 8640)          # day 6
    h = np.asarray(y["h"], np.float64)
    zeta = np.asarray(vorticity_cov(grid, model._fill_u(y["u"])),
                      np.float64)
    lat = np.asarray(grid.interior(grid.lat))
    zN = np.abs(zeta)[lat > 0.2].max()
    zS = np.abs(zeta)[lat < -0.2].max()
    mass = abs(np.sum(area * h) - m0) / m0
    ok = (bool(np.all(np.isfinite(h))) and 8500.0 < h.min()
          and h.max() < 10800.0 and mass < 1e-3
          and 5e-5 < zN < 5e-4 and zS < 5e-6)
    log(f"gate Galewsky C{n} nu4 ({nu4_mode}) day-6: "
        f"finite={np.all(np.isfinite(h))} "
        f"h_range=[{h.min():.0f},{h.max():.0f}] (in (8500,10800)) "
        f"mass_drift={mass:.2e} (<1e-3) max|zeta| N={zN:.2e} "
        f"(in (5e-5,5e-4)) S={zS:.2e} (<5e-6, quiescent hemisphere)")
    if not ok:
        log("gate Galewsky: FAILED — variant reported as 0")
        return 0.0, 0.0

    rate, out = steady_state_rate(lambda y, k: run(y, k), y,
                                  k1=2000, k2=8000)
    if not np.all(np.isfinite(np.asarray(out["h"]))):
        log("bench variant galewsky: non-finite after timing — 0")
        return 0.0, 0.0
    v = rate * dt / 86400.0
    log(f"bench variant galewsky-nu4 ({nu4_mode}): {rate:.1f} steps/s "
        f"-> {v:.4f} sim-days/sec/chip ({v / BASELINE_PER_CHIP:.4f}x "
        f"baseline; {nu4_mode} del^4 filter stepper, dt=60)")
    return v, rate


def bench_ensemble(n=96, dt=300.0, members=(1, 4, 16), warm=6,
                   k1=2000, k2=8000, gates=True, bitwise_check=True):
    """Batched ensemble section: aggregate throughput for B members.

    The many-concurrent-simulations workload (perturbed-IC TC5
    ensembles): one batched stepper call advances all B members, the
    member axis folded into the fused stage kernels' grid
    (make_fused_ssprk3_cov_compact(ensemble=B)) so small per-member
    grids stop paying per-call dispatch/DMA glue once per member.

    Default configuration: **C96 at the CFL-matched dt=300** — the TC5
    gate config — not the headline C384.  Ensembles are a
    members-x-moderate-resolution workload by nature, and that is
    where batching pays: at C96 the per-member step is small enough
    that fixed per-call glue (dispatch, DMA setup, router op dispatch)
    is a large step-time fraction, so folding B members into one
    launch buys aggregate throughput; at C384 a single member already
    fills the VPU and B mostly amortizes the residual glue.  (Pass
    n=384 to measure that regime explicitly.)
    Reports, per B: batched ensemble-steps/s, member-steps/s, AGGREGATE
    sim-days/sec/chip (the serving metric — total simulated days
    delivered across members), and a B-scaled roofline (flops AND bytes
    x B: truthful intensity).  Also records the B=1 batched-vs-unbatched
    bitwise check (the batching acceptance criterion) and the batched-
    exchange payload accounting for the largest B.  Falls back to the
    vmapped classic stepper (impl tag) where the fused kernels don't
    compile, so the section runs end-to-end on any backend; ``gates``
    off skips the physical-range checks (the --smoke mode).
    ``bitwise_check`` off skips the standalone B=1 batched-vs-unbatched
    jit (one full stepper compile, ~15 s on this CPU): the smoke tier
    leaves that exact parity to
    tests/test_ensemble.py::test_b1_batched_bitwise_vs_unbatched, which
    runs in the same gate — the full bench keeps it inline.
    """
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import (perturbed_ensemble,
                                                      williamson_tc5)
    from jaxstream.stepping import integrate
    from jaxstream.utils.profiling import steady_state_rate

    out = {"dt": dt, "case": "tc5", "members": list(members)}
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)

    impl = "fused_kernel"
    step1j = y1 = None
    stepB_cache = {}
    try:
        model = CovariantShallowWater(
            grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
            backend="pallas")
        # The compile IS the availability probe: on CPU the fused
        # pallas kernels construct fine and only fail here ("Only
        # interpret mode is supported"), which is what routes the
        # section to the vmapped classic stepper.
        step1j = jax.jit(model.make_fused_step(dt))
        y1 = model.compact_state(model.initial_state(h_ext, v_ext))
        jax.block_until_ready(step1j(y1, jnp.float32(0.0)))
    except Exception as e:
        log(f"bench ensemble: fused stepper unavailable "
            f"({type(e).__name__}: {e}); using vmapped classic stepper")
        impl = "vmap_classic"
        model = CovariantShallowWater(
            grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext)
    out["impl"] = impl

    if impl == "fused_kernel" and bitwise_check:
        # B=1 batched path must be bitwise-identical to the unbatched
        # stepper (the acceptance criterion of the member-axis fold).
        # The B=1 stepper is cached for the rate loop below; one jitted
        # unbatched stepper serves warm-up and check (the relay pays
        # ~1-40 s per compile — don't trace twice).
        try:
            stepB_cache[1] = model.make_fused_step(dt, ensemble=1)
            yb1 = model.ensemble_compact_state(
                model.stack_ensemble([model.initial_state(h_ext, v_ext)]))
            ob = jax.jit(stepB_cache[1])(yb1, jnp.float32(0.0))
            o1 = step1j(y1, jnp.float32(0.0))
            same = all(bool(jnp.all(
                (ob[k][:, 0] if k == "u" else ob[k][0]) == o1[k]))
                for k in o1)
            out["b1_bitwise"] = bool(same)
            log(f"bench ensemble: B=1 batched vs unbatched "
                f"bitwise={same}")
        except Exception as e:
            out["b1_bitwise"] = f"unavailable ({type(e).__name__}: {e})"

    h_b = perturbed_ensemble(grid, h_ext, max(members), seed=0,
                             amplitude=1e-3)

    def mk_run(stepB):
        return jax.jit(lambda y, k: integrate(stepB, y, 0.0, k, dt)[0],
                       donate_argnums=0)

    rates = {}
    for B in members:
        try:
            states = [model.initial_state(h_b[i], v_ext)
                      for i in range(B)]

            def build_carry():
                # Fresh carry per measurement attempt: runB DONATES its
                # input, so a retry can never reuse consumed buffers
                # (the per-member `states` are untouched by stacking).
                b = model.stack_ensemble(states)
                return (model.ensemble_compact_state(b)
                        if impl == "fused_kernel" else b)

            if impl == "fused_kernel":
                stepB = stepB_cache.get(B)
                if stepB is None:
                    stepB = model.make_fused_step(dt, ensemble=B)
            else:
                from jaxstream.parallel.sharded_model import \
                    make_stepper_for

                stepB = make_stepper_for(model, None, build_carry(), dt,
                                         "ssprk3", ensemble=B)
            runB = mk_run(stepB)
            yB = runB(build_carry(), warm)
            jax.block_until_ready(yB["h"])
            try:
                rate, outB = steady_state_rate(
                    lambda y, k: runB(y, k), yB, k1=k1, k2=k2)
            except Exception:
                # Tiny smoke windows can land t2 <= t1; one plain
                # window (on a rebuilt, re-warmed carry — yB was
                # donated by the failed attempt) is accurate enough
                # for a bitrot canary.
                yB = runB(build_carry(), warm)
                jax.block_until_ready(yB["h"])
                t0 = time.perf_counter()
                outB = runB(yB, k2)
                jax.block_until_ready(outB["h"])
                rate = k2 / (time.perf_counter() - t0)
            hB = np.asarray(outB["h"], np.float64)
            finite = bool(np.all(np.isfinite(hB)))
            ok = finite and (not gates
                             or (3000.0 < hB.min() and hB.max() < 6500.0))
            if not ok:
                log(f"bench ensemble B={B}: gate breached (finite="
                    f"{finite}, h=[{hB.min():.0f},{hB.max():.0f}]) — "
                    "entry reported as 0")
                rates[B] = None
                out[f"B{B}"] = {"sim_days_per_sec": 0.0}
                continue
            agg = rate * B * dt / 86400.0
            rates[B] = agg
            out[f"B{B}"] = _variant_entry(agg, rate, n, ensemble=B,
                                          dt=dt)
            log(f"bench ensemble B={B}: {rate:.2f} ensemble-steps/s "
                f"({rate * B:.1f} member-steps/s) -> {agg:.4f} "
                "aggregate sim-days/sec/chip")
        except Exception as e:
            log(f"bench ensemble B={B} unavailable "
                f"({type(e).__name__}: {e})")
            rates[B] = None
            out[f"B{B}"] = {"skipped": f"{type(e).__name__}: {e}"}
    b0, bN = members[0], members[-1]
    if rates.get(b0) and rates.get(bN):
        out["agg_speedup"] = {"vs": f"B{bN}/B{b0}",
                              "x": round(rates[bN] / rates[b0], 4)}
        log(f"bench ensemble: aggregate throughput B{bN}/B{b0} = "
            f"{rates[bN] / rates[b0]:.3f}x")
    try:
        from jaxstream.utils.comm_probe import batched_exchange_plan

        out["batched_exchange_plan"] = batched_exchange_plan(n, 2, bN)
    except Exception as e:
        log(f"bench ensemble: exchange plan unavailable ({e})")
    return out


def bench_serving(n=96, dt=300.0, bucket=16, n_requests=48, seg=8,
                  backend="pallas", lengths=None, ic="tc5", gates=True):
    """Serving section: continuous batching vs serial B=1 (round 11).

    The throughput headline of the ensemble server (jaxstream.serve):
    ``n_requests`` heterogeneous-run-length scenario requests (same IC
    family, distinct perturbation seeds, lengths cycling a ragged
    ladder so members finish mid-batch and slots refill continuously)
    are served twice —

      * **packed**: one bucket of size ``bucket`` — requests ride the
        member axis, per-member masking + boundary refill keep the
        slots busy;
      * **serial_B1**: the same trace through a B=1 bucket — the
        no-batching reference every request-at-a-time deployment runs.

    Reports per mode: aggregate member-steps/s and sim-days/sec/chip
    (the serving metric), slot occupancy and step utilization, request
    latency p50/p99 (requests are all admitted up front, so the serial
    tail latency IS the queue wait the packed mode removes), warmup
    compile count, and the steady-state recompile count (must be 0 —
    the shape-bucketing claim).  ``main()`` divides the packed
    member-steps/s by the ensemble section's static-B=16 rate: the
    acceptance floor is >= 0.9x (masking + refill overhead must stay
    under 10% of the PR-3 batched rate).  Warmup/compile time is
    excluded from the timed window (steady-state serving).  Never
    raises (returns ``{"skipped": ...}``).
    """
    try:
        from jaxstream.serve import EnsembleServer, ScenarioRequest

        if lengths is None:
            lengths = (seg * 3, seg * 5 + 3, seg * 2 + 1, seg * 7,
                       seg * 4 + 5)
        out = {"n": n, "dt": dt, "bucket": bucket,
               "n_requests": n_requests, "segment_steps": seg,
               "ic": ic, "lengths": list(lengths)}
        group = "oro" if ic == "tc5" else "flat"

        def mk_requests():
            return [ScenarioRequest(
                id=f"r{i}", ic=ic, nsteps=lengths[i % len(lengths)],
                seed=i, amplitude=1e-3)
                for i in range(n_requests)]

        def run_mode(b):
            # group_by_orography: true pins the round-11 code path
            # (orography a stepper static, fused member-fold where it
            # compiles) so this section's numbers stay byte-for-byte
            # comparable across rounds; the single-family trace never
            # exercises mixed batches anyway.  The mixed and multichip
            # paths are measured by bench_serving_multichip.
            cfg = {"grid": {"n": n, "halo": 2, "dtype": "float32"},
                   "time": {"dt": dt},
                   "model": {"name": "shallow_water_cov",
                             "backend": backend},
                   "serve": {"buckets": str(b), "segment_steps": seg,
                             "queue_capacity": n_requests + 1,
                             "group_by_orography": True}}
            srv = EnsembleServer(cfg)
            try:
                srv.warmup(groups=(group,))       # compiles excluded
                for r in mk_requests():
                    srv.submit(r)
                t0 = time.perf_counter()
                srv.serve()
                wall = time.perf_counter() - t0
                lat = srv.latencies()
                ms = srv.stats["member_steps"]
                entry = {
                    "completed": srv.stats["completed"],
                    "evicted": srv.stats["evicted"],
                    "segments": srv.stats["segments"],
                    "refills": srv.stats["refills"],
                    "occupancy_mean": round(srv.occupancy_mean, 4),
                    "utilization_mean": round(srv.utilization_mean, 4),
                    "member_steps": ms,
                    "member_steps_per_sec": round(ms / wall, 2),
                    "agg_sim_days_per_sec_per_chip": round(
                        ms * dt / 86400.0 / wall, 4),
                    "latency_p50_s": round(
                        float(np.percentile(lat, 50)), 4),
                    "latency_p99_s": round(
                        float(np.percentile(lat, 99)), 4),
                    "warmup_compiles": srv.stats["warmup_compiles"],
                    "steady_recompiles": (
                        srv.compile_count()
                        - srv.stats["warmup_compiles"]),
                    "impl": srv._impls.get(group),
                    "wall_s": round(wall, 3),
                }
                if srv.stats["completed"] != n_requests:
                    raise RuntimeError(
                        f"serving B={b}: only {srv.stats['completed']}"
                        f"/{n_requests} requests completed")
                if gates:
                    for r in srv.results.values():
                        h = np.asarray(r.fields["h"], np.float64)
                        if not (np.all(np.isfinite(h))
                                and 3000.0 < h.min()
                                and h.max() < 6500.0):
                            raise RuntimeError(
                                f"serving B={b}: request {r.id} gate "
                                f"breached (h=[{h.min():.0f},"
                                f"{h.max():.0f}])")
                return entry
            finally:
                srv.close()

        out["packed"] = run_mode(bucket)
        out["serial_B1"] = run_mode(1)
        p, s = (out["packed"]["member_steps_per_sec"],
                out["serial_B1"]["member_steps_per_sec"])
        out["packed_vs_serial"] = round(p / s, 4) if s else None
        log(f"bench serving C{n} {ic} {n_requests} reqs "
            f"(bucket {bucket}, seg {seg}): packed "
            f"{p:.1f} member-steps/s (occ "
            f"{out['packed']['occupancy_mean']:.2f}, p50/p99 "
            f"{out['packed']['latency_p50_s']:.2f}/"
            f"{out['packed']['latency_p99_s']:.2f}s, "
            f"{out['packed']['steady_recompiles']} steady recompiles) "
            f"vs serial-B1 {s:.1f} -> {out['packed_vs_serial']}x")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench serving: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_serving_multichip(n=96, dt=300.0, per_chip=4, seg=8,
                            reqs_per_chip=6, mode="member",
                            devices=0, backend="jnp", ic="tc2",
                            lengths=None, gates=True):
    """Multi-chip serving section (round 12): N-device scaling floor.

    The acceptance measurement of serve.placement: the SAME ragged
    per-chip traffic is served twice at equal per-chip batch —

      * **single**: one device, bucket ``per_chip`` (placement off —
        the round-11 executable, byte-for-byte);
      * **multichip**: all ``N`` devices, bucket ``per_chip * N``
        under the requested placement mode, with ``N`` x the request
        count (so each chip sees the same steady-state load).

    Reports per mode: aggregate member-steps/s and sim-days/sec (the
    serving metric — NOT per chip: the whole point is that one server
    process now delivers N chips' worth), occupancy/utilization,
    steady-recompile counts, and the scaling ratio
    ``aggregate_multichip / (N * aggregate_single)``.  The acceptance
    floor ``>= 0.8`` (``meets_0p8_floor``) is ENFORCED — reported as a
    gate breach — only on real accelerators: on the fake-device CPU
    mesh (the MULTICHIP-gate test environment, also used by the smoke
    canary) all N "devices" share one host's cores, so the ratio
    measures XLA's partitioned-executable overhead, not scaling, and
    is reported with ``floor_enforced: false``.  The single-device
    parity claim IS asserted everywhere: packed h results must be
    byte-identical between the modes (u carries the established
    <= 1e-6 member-batching budget) — ``bitwise_h_ok``.

    Never raises (returns ``{"skipped": ...}``) — e.g. when fewer than
    2 devices exist in-process.
    """
    try:
        import jax

        from jaxstream.serve import EnsembleServer, ScenarioRequest

        n_dev = devices or len(jax.devices())
        if n_dev < 2:
            return {"skipped": f"needs >= 2 devices, have {n_dev}"}
        platform = jax.devices()[0].platform
        enforce = platform not in ("cpu",)
        if lengths is None:
            lengths = (seg * 3, seg * 5 + 3, seg * 2 + 1, seg * 4)
        out = {"n": n, "dt": dt, "per_chip": per_chip,
               "segment_steps": seg, "devices": n_dev, "mode": mode,
               "platform": platform, "ic": ic,
               "floor_enforced": bool(enforce)}

        def run_mode(bucket, placement, n_requests):
            cfg = {"grid": {"n": n, "halo": 2, "dtype": "float32"},
                   "time": {"dt": dt},
                   "model": {"name": "shallow_water_cov",
                             "backend": backend},
                   "serve": {"buckets": str(bucket),
                             "segment_steps": seg,
                             "queue_capacity": n_requests + 1,
                             # panel placement bakes orography per
                             # device (grouped mode); both runs use
                             # the same flag so the parity compare is
                             # stepper-for-stepper.
                             "group_by_orography": mode == "panel"}}
            if placement is not None:
                cfg["serve"]["placement"] = placement
            srv = EnsembleServer(cfg)
            try:
                srv.warmup(groups=("flat",))      # compiles excluded
                for i in range(n_requests):
                    srv.submit(ScenarioRequest(
                        id=f"r{i}", ic=ic,
                        nsteps=lengths[i % len(lengths)],
                        seed=i % reqs_per_chip, amplitude=1e-3,
                        outputs=("h", "u")))
                t0 = time.perf_counter()
                srv.serve()
                wall = time.perf_counter() - t0
                ms = srv.stats["member_steps"]
                if srv.stats["completed"] != n_requests:
                    raise RuntimeError(
                        f"only {srv.stats['completed']}/{n_requests} "
                        f"requests completed")
                entry = {
                    "completed": srv.stats["completed"],
                    "segments": srv.stats["segments"],
                    "refills": srv.stats["refills"],
                    "occupancy_mean": round(srv.occupancy_mean, 4),
                    "utilization_mean": round(srv.utilization_mean, 4),
                    "member_steps": ms,
                    "member_steps_per_sec": round(ms / wall, 2),
                    "agg_sim_days_per_sec": round(
                        ms * dt / 86400.0 / wall, 4),
                    "host_wait_s": round(srv.stats["host_wait_s"], 4),
                    "steady_recompiles": (
                        srv.compile_count()
                        - srv.stats["warmup_compiles"]),
                    "wall_s": round(wall, 3),
                }
                if placement is not None:
                    entry["placement"] = srv.placement_summary()
                results = {rid: r.fields for rid, r in
                           srv.results.items()}
                return entry, results
            finally:
                srv.close()

        # Equal per-chip batch and load: the single-device reference
        # serves reqs_per_chip requests through a per_chip bucket; the
        # multichip run serves N x as many through a per_chip*N bucket.
        out["single"], res1 = run_mode(per_chip, None, reqs_per_chip)
        out["multichip"], resN = run_mode(
            per_chip * n_dev,
            {"mode": mode, "num_devices": n_dev,
             "device_type": "default" if platform != "cpu" else "cpu"},
            reqs_per_chip * n_dev)

        # Parity on the shared request ids (same seed + length).
        # Member mode runs the SAME program GSPMD-partitioned: h must
        # be byte-identical across placements, u within the 2e-6
        # packed-vs-packed member-batching budget.  Panel mode runs a
        # DIFFERENT RHS implementation (shard_map per-face kernels +
        # strip exchange vs the classic oracle): both fields carry the
        # established cross-tier <= 1e-6 budget instead — bitwise is
        # not the contract there (docs/USAGE.md "Multi-chip serving").
        bitwise = True
        h_rel_max = u_rel_max = 0.0
        for rid, f1 in res1.items():
            fN = resN.get(rid)
            if fN is None:
                continue
            if np.asarray(f1["h"]).tobytes() != \
                    np.asarray(fN["h"]).tobytes():
                bitwise = False
            for key in ("h", "u"):
                a = np.asarray(fN[key], np.float64)
                b = np.asarray(f1[key], np.float64)
                rel = float(np.abs(a - b).max() / np.abs(b).max())
                if key == "h":
                    h_rel_max = max(h_rel_max, rel)
                else:
                    u_rel_max = max(u_rel_max, rel)
        out["bitwise_h_ok"] = bool(bitwise)
        out["h_rel_max"] = h_rel_max
        out["u_rel_max"] = u_rel_max
        sm, ss = (out["multichip"]["member_steps_per_sec"],
                  out["single"]["member_steps_per_sec"])
        ratio = sm / (n_dev * ss) if ss else None
        out["scaling_vs_ideal"] = (round(ratio, 4)
                                   if ratio is not None else None)
        out["meets_0p8_floor"] = (bool(ratio >= 0.8)
                                  if ratio is not None else None)
        out["zero_steady_recompiles"] = bool(
            out["single"]["steady_recompiles"] == 0
            and out["multichip"]["steady_recompiles"] == 0)
        log(f"bench serving_multichip C{n} {mode} x{n_dev} "
            f"({platform}): {sm:.1f} member-steps/s aggregate vs "
            f"single {ss:.1f} -> {out['scaling_vs_ideal']}x of ideal "
            f"N-chip scaling (floor 0.8 "
            f"{'ENFORCED' if enforce else 'reported only — fake CPU mesh'}"
            f"), bitwise_h={out['bitwise_h_ok']}, "
            f"h_rel={h_rel_max:.2e}, u_rel={u_rel_max:.2e}, "
            f"{out['multichip']['steady_recompiles']} steady recompiles")
        if gates:
            if mode == "panel":
                if max(h_rel_max, u_rel_max) > 1e-6:
                    raise RuntimeError(
                        f"serving_multichip: panel-sharded parity "
                        f"h={h_rel_max:.3e} u={u_rel_max:.3e} exceeds "
                        f"the cross-tier 1e-6 budget")
            else:
                if not out["bitwise_h_ok"]:
                    raise RuntimeError(
                        "serving_multichip: packed h diverged between "
                        "single-device and member-parallel placements")
                # Each packed run sits within 1e-6 of the solo
                # trajectory (the member-batching budget); two packed
                # runs at different bucket sizes are therefore within
                # 2e-6 of each other (triangle inequality — observed
                # ~1e-8).
                if u_rel_max > 2e-6:
                    raise RuntimeError(
                        f"serving_multichip: u rel {u_rel_max:.3e} "
                        f"exceeds the 2e-6 packed-vs-packed budget")
            if not out["zero_steady_recompiles"]:
                raise RuntimeError(
                    "serving_multichip: steady-state serving "
                    "recompiled under placement")
            if enforce and not out["meets_0p8_floor"]:
                raise RuntimeError(
                    f"serving_multichip: {out['scaling_vs_ideal']}x of "
                    f"ideal N-chip scaling breaches the 0.8 floor")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench serving_multichip: unavailable "
            f"({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_serving_slo(n=96, dt=300.0, n_requests=64, seed=1404,
                      buckets="1,4,16", seg=8, backend="jnp",
                      queue_capacity=24, lengths=None,
                      mean_gap_s=0.02, tail_alpha=1.4,
                      max_workers=8, p99_floor_s=None,
                      goodput_floor_frac=0.5, packed_msps=None,
                      gates=True):
    """Serving-SLO section (round 14): the network front door under a
    closed-loop heavy-tailed load, with enforced floors.

    An in-process :class:`jaxstream.gateway.Gateway` binds loopback; a
    deterministic mixed-IC trace (tc2/tc5/tc6/galewsky, ragged
    lengths, heavy-tailed Pareto arrivals — ``jaxstream.loadgen``) is
    replayed against it over real HTTP by a bounded worker pool while
    the autoscale policy resizes the active bucket cap live from queue
    depth + occupancy.  This measures what the throughput sections
    cannot: REQUEST latency percentiles (submit-to-result wall time
    through admission, queueing, packing, streaming), goodput
    (member-steps of completed work per second), and the overload
    contract (every request completes or sheds as a typed 429/503).

    Floors (``gates=True``; breaches surface as ``skipped`` with the
    reason, like the sibling serving sections):

      * accounting exactness — completed + typed-shed == submitted,
        zero untyped errors;
      * >= 1 live autoscale resize (the burst must trip the policy);
      * ZERO steady-state recompiles after warmup, resizes included
        (every level maps to a warm bucket by construction — this
        asserts it);
      * goodput >= ``goodput_floor_frac`` x the packed serving rate
        (``packed_msps``, member-steps/s from ``bench_serving`` —
        main() threads it through; the HTTP+streaming front door may
        cost at most half the engine's rate at this scale);
      * request p99 <= ``p99_floor_s`` when given (absolute SLO for
        the calibrated TPU config; None = reported only).

    Never raises (returns ``{"skipped": ...}``).
    """
    try:
        import os
        import shutil
        import tempfile

        from jaxstream.gateway import Gateway
        from jaxstream.gateway.client import get_text
        from jaxstream.loadgen import (AutoscaleController,
                                       AutoscalePolicy, generate_trace,
                                       run_load)
        from jaxstream.obs.registry import parse_exposition

        levels = tuple(sorted({int(b) for b in buckets.split(",")
                               if b.strip()}))
        if lengths is None:
            lengths = (seg * 2, seg * 3 + 1, seg, seg * 5 + 3)
        out = {"n": n, "dt": dt, "n_requests": n_requests,
               "buckets": buckets, "segment_steps": seg, "seed": seed,
               "lengths": list(lengths),
               "queue_capacity": queue_capacity}
        # Round 17: the section runs with request tracing ON and
        # certifies trace coverage — every completed request must
        # reassemble into a full span tree (spans_complete == 1.0),
        # and /v1/metrics must serve a parseable Prometheus payload.
        sink_dir = tempfile.mkdtemp(prefix="jaxstream_slo_")
        serve_sink = os.path.join(sink_dir, "serve.jsonl")
        gw_sink = os.path.join(sink_dir, "gateway.jsonl")
        cfg = {"grid": {"n": n, "halo": 2, "dtype": "float32"},
               "time": {"dt": dt},
               "model": {"name": "shallow_water_cov",
                         "backend": backend},
               "serve": {"buckets": buckets, "segment_steps": seg,
                         "queue_capacity": queue_capacity,
                         "sink": serve_sink, "trace": True}}
        ctrl = AutoscaleController(AutoscalePolicy(
            levels=levels, queue_high=3, queue_low=0, occ_low=0.6,
            patience=2, cooldown=2))
        trace = generate_trace(n_requests, seed,
                               mean_gap_s=mean_gap_s,
                               tail_alpha=tail_alpha, lengths=lengths)
        out["families"] = sorted({e["ic"] for e in trace})
        gw = Gateway(cfg, host="127.0.0.1", port=0, autoscale=ctrl,
                     sink=gw_sink)
        try:
            gw.start()
            out["warm_compiles"] = gw.warm_compiles
            summary = run_load("127.0.0.1", gw.port, trace,
                               time_scale=1.0, max_workers=max_workers,
                               dt=dt, trace_spans=True,
                               span_sinks=[serve_sink, gw_sink])
            out["slo"] = summary
            out["autoscale"] = ctrl.summary()
            out["steady_recompiles"] = (gw.server.compile_count()
                                        - gw.warm_compiles)
            out["resizes"] = len(ctrl.events)
            # Scrape the live gateway: the payload must parse as text
            # exposition 0.0.4 (the structural checks — +Inf buckets,
            # monotone cumulative counts — live in the parser).
            status, ctype, text = get_text("127.0.0.1", gw.port,
                                           "/v1/metrics")
            parsed = parse_exposition(text)
            out["metrics_scrape"] = {
                "status": status,
                "content_type": ctype,
                "families": len(parsed["types"]),
                "samples": sum(len(v)
                               for v in parsed["samples"].values()),
                "submitted": parsed["samples"].get(
                    "jaxstream_requests_submitted_total", {}).get(""),
                "ok": bool(status == 200
                           and "version=0.0.4" in ctype
                           and parsed["types"]),
            }
        finally:
            gw.close()
            shutil.rmtree(sink_dir, ignore_errors=True)
        msps = summary["goodput_member_steps_per_sec"]
        if packed_msps:
            out["goodput_vs_packed"] = round(msps / packed_msps, 4)
            out["meets_goodput_floor"] = bool(
                msps >= goodput_floor_frac * packed_msps)
        if p99_floor_s is not None:
            out["p99_floor_s"] = p99_floor_s
            out["meets_p99_floor"] = bool(
                summary["latency_p99_s"] is not None
                and summary["latency_p99_s"] <= p99_floor_s)
        log(f"bench serving_slo C{n} {n_requests} reqs over HTTP "
            f"loopback (buckets {buckets}): "
            f"{summary['completed']} completed / {summary['shed']} "
            f"shed / {summary['errors']} errors; p50/p99 "
            f"{summary['latency_p50_s']}/{summary['latency_p99_s']}s; "
            f"goodput {msps} member-steps/s; {out['resizes']} "
            f"autoscale resize(s); {out['steady_recompiles']} steady "
            f"recompiles; spans_complete "
            f"{summary.get('spans_complete')} over "
            f"{summary.get('spans_checked')} trees; metrics scrape "
            f"{out['metrics_scrape']['families']} families")
        if gates:
            if not summary["accounting_exact"]:
                raise RuntimeError(
                    f"serving_slo: overload contract broken — "
                    f"{summary['errors']} untyped outcomes of "
                    f"{summary['n_requests']} (completed "
                    f"{summary['completed']}, shed {summary['shed']})")
            if out["resizes"] < 1:
                raise RuntimeError(
                    "serving_slo: the heavy-tailed burst tripped no "
                    "autoscale resize — the closed loop is not "
                    "exercising the policy")
            if out["steady_recompiles"] != 0:
                raise RuntimeError(
                    f"serving_slo: {out['steady_recompiles']} steady-"
                    f"state recompiles after warmup/resizes — the "
                    "warm-bucket claim is broken")
            if summary.get("spans_complete") != 1.0:
                raise RuntimeError(
                    f"serving_slo: trace coverage broken — "
                    f"spans_complete {summary.get('spans_complete')} "
                    f"over {summary.get('spans_checked')} requests "
                    f"(failures: {summary.get('span_failures')})")
            if not out["metrics_scrape"]["ok"]:
                raise RuntimeError(
                    f"serving_slo: /v1/metrics scrape is not valid "
                    f"Prometheus text exposition: "
                    f"{out['metrics_scrape']}")
            if packed_msps and not out["meets_goodput_floor"]:
                raise RuntimeError(
                    f"serving_slo: goodput {msps} member-steps/s is "
                    f"below {goodput_floor_frac} x the packed serving "
                    f"rate ({packed_msps})")
            if p99_floor_s is not None and not out["meets_p99_floor"]:
                raise RuntimeError(
                    f"serving_slo: p99 {summary['latency_p99_s']}s "
                    f"breaches the {p99_floor_s}s floor")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench serving_slo: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_assimilation(n=48, dt=300.0, members=8, cycles=4,
                       cycle_steps=8, nstations=128, obs_sigma=1.0,
                       amplitude=1.0e-3, gates=True):
    """Assimilation section (round 18): the forecast claim.

    Runs the in-process EnKF cycle (jaxstream.da) on the Galewsky jet
    — a hidden truth run observed through ``nstations`` seeded
    stations every ``cycle_steps`` steps, a ``members``-member
    perturbed ensemble pulled toward the observations by the
    stochastic B x B ensemble-space analysis — and the FREE ensemble
    under identical seeds as the baseline.  The headline is the gated
    forecast claim: the cycled ensemble-mean RMSE vs the hidden truth
    must BEAT the free-running ensemble's (``beats_free_run``); the
    calibrated config must also finish with zero guard events (a
    spread collapse or filter divergence here means the defaults
    regressed).  Never raises (returns ``{"skipped": ...}``).
    """
    try:
        from jaxstream.da import run_cycle

        cfg = {
            "grid": {"n": n},
            "time": {"dt": dt},
            "model": {"name": "shallow_water_cov", "backend": "jnp",
                      "initial_condition": "galewsky"},
            "parallelization": {"num_devices": 1},
            "ensemble": {"members": members, "seed": 5,
                         "amplitude": amplitude},
            "da": {"cycles": cycles, "cycle_steps": cycle_steps,
                   "nstations": nstations, "obs_sigma": obs_sigma},
        }
        t0 = time.perf_counter()
        cycled = run_cycle(cfg)
        free = run_cycle(cfg, assimilate=False)
        out = {
            "n": n, "dt": dt, "members": members, "cycles": cycles,
            "cycle_steps": cycle_steps, "nstations": nstations,
            "obs_sigma": obs_sigma,
            "plan": cycled["plan"],
            "proof_verdict": cycled["proof_verdict"],
            "cycled_final_rmse": cycled["final_rmse"],
            "cycled_mean_rmse": round(cycled["mean_rmse"], 6),
            "cycled_final_spread": cycled["final_spread"],
            "free_final_rmse": free["final_rmse"],
            "free_mean_rmse": round(free["mean_rmse"], 6),
            "rmse_reduction": round(
                free["final_rmse"] - cycled["final_rmse"], 6),
            "beats_free_run": bool(
                cycled["final_rmse"] < free["final_rmse"]),
            "guard_events": len(cycled["guard_events"]),
            "cycle_records": cycled["cycles"],
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        log(f"bench assimilation C{n} galewsky B={members} "
            f"({cycles} cycles x {cycle_steps} steps, {nstations} "
            f"stations): cycled rmse {out['cycled_final_rmse']:.4f} "
            f"vs free {out['free_final_rmse']:.4f} "
            f"({'BEATS' if out['beats_free_run'] else 'LOSES TO'} "
            f"the free run; {out['guard_events']} guard events)")
        if gates:
            if not out["beats_free_run"]:
                raise RuntimeError(
                    f"assimilation: cycled final RMSE "
                    f"{out['cycled_final_rmse']} does not beat the "
                    f"free ensemble's {out['free_final_rmse']} — the "
                    f"forecast claim is the section's headline gate")
            if out["guard_events"]:
                raise RuntimeError(
                    f"assimilation: {out['guard_events']} guard "
                    f"event(s) on the calibrated config — filter "
                    f"health regressed")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench assimilation: unavailable "
            f"({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_io(n=48, dt=600.0, nsteps=96, stride=12, warm=12, ic="tc2",
             gates=True):
    """IO-overlap section: history+telemetry cost, async vs sync vs off.

    The async-host-pipeline acceptance measurement (round 9): the same
    Simulation config is integrated three ways — no IO at all (the
    baseline the device loop can reach), history+checkpoint+telemetry
    with the synchronous boundary stalls, and the same IO under
    ``io.async_pipeline`` (double-buffered fetches + background
    writers).  Reports steps/s for each, the IO overhead of both modes
    relative to the io-off baseline, and the per-segment
    ``host_wait_s`` totals from the runs' own telemetry files (the
    async column is the overlap made visible).  ``gates``: the final
    height field must stay finite in every mode — a pipeline that
    corrupts the carry must not report a rate.  Never raises (returns
    ``{"skipped": ...}``) — the headline metric does not depend on it.

    Fairness: the simulation logger is held at WARNING for every mode,
    which suppresses the sync path's per-emit diagnostics log lines
    (a diagnostics compute + blocking device_get per boundary that the
    async loop never performs).  Both modes therefore do identical I/O
    work — history append + checkpoint save + telemetry record — and
    the sync/async delta measures *overlap*, not dropped work.
    """
    import logging
    import shutil
    import tempfile

    from jaxstream.obs.sink import read_records
    from jaxstream.simulation import Simulation

    out = {"n": n, "dt": dt, "nsteps": nsteps, "stride": stride,
           "ic": ic}

    def run_mode(mode):
        d = tempfile.mkdtemp(prefix=f"bench_io_{mode}_")
        cfg = {
            "grid": {"n": n, "halo": 2, "dtype": "float32"},
            "model": {"initial_condition": ic},
            "time": {"dt": dt, "nsteps": warm + nsteps},
            "parallelization": {"num_devices": 1},
        }
        if mode != "off":
            cfg["io"] = {
                "history_path": d + "/hist", "history_stride": stride,
                "checkpoint_path": d + "/ckpt",
                "checkpoint_stride": stride,
                "async_pipeline": {"enabled": mode == "async"},
            }
            cfg["observability"] = {"interval": stride,
                                    "sink": d + "/telemetry.jsonl"}
        sim = Simulation(cfg)
        try:
            sim.run(warm)                      # compile + first strides
            t0 = time.perf_counter()
            if mode == "off":
                # No strides -> one run() call would jit a SECOND,
                # different-length segment inside the timed window
                # (deflating the baseline that io_overhead_pct divides
                # by).  Advance in warm-sized calls so the timed window
                # reuses the already-compiled k=warm segment, like the
                # strided modes reuse theirs.
                s = warm
                while s < warm + nsteps:
                    s = min(s + warm, warm + nsteps)
                    sim.run(s)
            else:
                sim.run(warm + nsteps)
            wall = time.perf_counter() - t0
            h = np.asarray(sim.state["h"], np.float64)
            finite = bool(np.all(np.isfinite(h)))
            if gates and not finite:
                raise RuntimeError(f"bench io mode={mode}: non-finite h")
            entry = {"steps_per_sec": round(nsteps / wall, 2),
                     "wall_s": round(wall, 3)}
            if mode != "off":
                segs = read_records(d + "/telemetry.jsonl",
                                    kind="segment")
                entry["host_wait_s_total"] = round(
                    sum(s.get("host_wait_s", 0.0) for s in segs
                        if s["step"] > warm), 4)
            return entry
        finally:
            sim.close()
            shutil.rmtree(d, ignore_errors=True)

    sim_log = logging.getLogger("jaxstream.simulation")
    old_level = sim_log.level
    sim_log.setLevel(logging.WARNING)
    try:
        for mode in ("off", "sync", "async"):
            out[mode] = run_mode(mode)
        base = out["off"]["steps_per_sec"]
        for mode in ("sync", "async"):
            r = out[mode]["steps_per_sec"]
            out[mode]["io_overhead_pct"] = round(100.0 * (base / r - 1.0),
                                                 2)
        out["async_overhead_smaller"] = (
            out["async"]["io_overhead_pct"]
            < out["sync"]["io_overhead_pct"])
        log(f"bench io C{n} {ic} {nsteps} steps (stride {stride}): "
            f"off {base:.1f} steps/s; "
            f"sync {out['sync']['steps_per_sec']:.1f} "
            f"(+{out['sync']['io_overhead_pct']:.1f}% overhead, host "
            f"wait {out['sync']['host_wait_s_total']:.3f}s); "
            f"async {out['async']['steps_per_sec']:.1f} "
            f"(+{out['async']['io_overhead_pct']:.1f}% overhead, host "
            f"wait {out['async']['host_wait_s_total']:.3f}s)")
    except Exception as e:  # never fail the headline metric on this
        log(f"bench io: unavailable ({type(e).__name__}: {e})")
        out["skipped"] = f"{type(e).__name__}: {e}"
    finally:
        sim_log.setLevel(old_level)
    return out


def compile_report(n=24):
    """``--compile-report``: cold vs warm compile seconds, one JSON line.

    Measures the persistent compilation cache (enabled by
    ``JAXSTREAM_COMPILE_CACHE=/path``, picked up on jaxstream import):
    compile a representative stepper executable cold, drop jax's
    in-memory caches (``jax.clear_caches()``), compile again — warm
    hits the persistent cache when enabled, recompiles when not, so the
    cold/warm ratio IS the cache's value.  Same-process reuse only: on
    this image's jaxlib a *different* process deserializing CPU cache
    entries segfaults (tests/conftest.py round-8 note), which is why
    the cache is an env-var opt-in rather than a default.
    """
    import os

    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water import ShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc2
    from jaxstream.stepping import integrate

    cache_dir = os.environ.get("JAXSTREAM_COMPILE_CACHE", "")
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = ShallowWater(grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA)
    state = model.initial_state(h_ext, v_ext)
    step = model.make_step(600.0, "ssprk3")
    fn = jax.jit(lambda y, k: integrate(step, y, 0.0, k, 600.0))

    t0 = time.perf_counter()
    fn.lower(state, 8).compile()
    cold = time.perf_counter() - t0
    jax.clear_caches()
    t0 = time.perf_counter()
    fn.lower(state, 8).compile()
    warm = time.perf_counter() - t0
    rec = {
        "metric": "compile_report",
        "cache_dir": cache_dir or None,
        "cache_enabled": bool(cache_dir),
        "n_cache_entries": (len(os.listdir(cache_dir))
                            if cache_dir and os.path.isdir(cache_dir)
                            else 0),
        "cold_compile_s": round(cold, 3),
        "warm_compile_s": round(warm, 3),
        "speedup": round(cold / warm, 2) if warm > 0 else None,
    }
    log(f"compile report (C{n} classic SSPRK3 segment): cold {cold:.2f}s "
        f"-> warm {warm:.2f}s "
        + (f"(persistent cache at {cache_dir}, "
           f"{rec['n_cache_entries']} entries)" if cache_dir
           else "(JAXSTREAM_COMPILE_CACHE unset: warm = plain recompile)"))
    print(json.dumps(rec))
    return 0


def bench_precision_report(n=384, dt=BENCH_DT, interpret=False,
                           warm=10, k1=1500, k2=6000):
    """``--precision-report``: the precision ladder measured side by
    side on one grid/IC/dt, so each column isolates ONE knob.

    Rows (round 10; jaxstream.ops.pallas.precision semantics):

      ``f32``           all-f32 reference (the headline stepper)
      ``bf16_stage``    bf16 stage ARITHMETIC (flux/recon/router ops;
                        f32 accumulators + metric terms, bf16 strips)
      ``mixed16_carry`` 16-bit carry STORAGE (h int16 + u bf16), f32
                        arithmetic — the round-5 encoding
      ``stacked``       both: bf16 stage arithmetic + 16-bit carry

    Each row reports steps/s, sim-days/sec/chip, speedup vs the f32
    row, and the precision-corrected roofline (``carry_bytes`` bytes
    model, bf16 flop fraction + mixed-roof percentage) — the honest-
    accounting half of the round-10 satellite.  TC5 ICs; NO physics
    gates here (the gated rates live in the ``variants`` section; this
    is the ladder comparison).  ``interpret=True`` runs the kernels in
    Pallas interpret mode with whatever windows the caller passes —
    the ``--smoke`` structural canary, not a measurement.
    """
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.ops.pallas.precision import (encode_strips,
                                                mixed16_encoding)
    from jaxstream.physics.initial_conditions import williamson_tc5
    from jaxstream.stepping import integrate
    from jaxstream.utils.profiling import steady_state_rate

    out = {"n": n, "dt": dt, "interpret": bool(interpret), "rows": {}}
    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)
    model = CovariantShallowWater(
        grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
        backend="pallas_interpret" if interpret else "pallas")
    st0 = model.initial_state(h_ext, v_ext)
    cd, off, hs = mixed16_encoding(st0["h"])

    def carry16(y):
        return model.encode_carry(y, cd, off, hs)

    def dec16(y):
        return model.decode_carry(y, h_offset=off, h_scale=hs)

    # (name, stepper kwargs, carry encode, carry decode, roofline kwargs)
    # — decode maps a row's carry back to absolute f32 h/u so the
    # non-finite guard sees real field values (an int16 h is ALWAYS
    # "finite"; bf16 u NaNs must be checked post-decode).
    rows = [
        ("f32", {}, lambda y: y, lambda y: y, {}),
        ("bf16_stage", {"precision": "bf16"},
         lambda y: encode_strips(y, "bf16"), lambda y: y,
         {"precision": "bf16"}),
        ("mixed16_carry",
         {"carry_dtype": cd, "h_offset": off, "h_scale": hs},
         carry16, dec16, {"carry_bytes": 2}),
        ("stacked",
         {"precision": "bf16", "carry_dtype": cd, "h_offset": off,
          "h_scale": hs},
         lambda y: encode_strips(carry16(y), "bf16"), dec16,
         {"precision": "bf16", "carry_bytes": 2}),
    ]
    def fresh_carry(enc):
        # Donation consumes the carry, and compact_state's output
        # aliases st0's buffers — copy every leaf so each row (and the
        # fallback window) starts from live arrays.
        return enc({k: jnp.copy(v)
                    for k, v in model.compact_state(st0).items()})

    for name, kw, enc, dec, rl_kw in rows:
        try:
            step = model.make_fused_step(dt, **kw)
            if interpret:
                # Smoke path: eager stage-kernel calls.  Wrapping the
                # loop in jit(integrate) costs ~35 s/row of interpret-
                # mode lowering vs ~9 s for the kernels alone (measured
                # C12 CPU) and adds no structural coverage — the jitted
                # donation loop is the measurement path below.
                y = fresh_carry(enc)
                for _ in range(warm):
                    y = step(y, 0.0)
                jax.block_until_ready(y["h"])
                t0 = time.perf_counter()
                outy = y
                for _ in range(k2):
                    outy = step(outy, 0.0)
                jax.block_until_ready(outy["h"])
                rate = k2 / (time.perf_counter() - t0)
            else:
                run = jax.jit(
                    lambda y, k, _s=step: integrate(_s, y, 0.0, k, dt)[0],
                    donate_argnums=0)
                y = run(fresh_carry(enc), warm)
                jax.block_until_ready(y["h"])
                try:
                    rate, outy = steady_state_rate(
                        lambda y, k: run(y, k), y, k1=k1, k2=k2)
                except Exception:
                    # Windows can land t2 <= t1 on a noisy host; one
                    # plain window on a rebuilt carry still reports.
                    y = run(fresh_carry(enc), warm)
                    jax.block_until_ready(y["h"])
                    t0 = time.perf_counter()
                    outy = run(y, k2)
                    jax.block_until_ready(outy["h"])
                    rate = k2 / (time.perf_counter() - t0)
            outd = dec(outy)
            if not (bool(jnp.all(jnp.isfinite(
                        outd["h"].astype(jnp.float32))))
                    and bool(jnp.all(jnp.isfinite(
                        outd["u"].astype(jnp.float32))))):
                raise RuntimeError("non-finite h/u after the rate window")
            row = {"steps_per_sec": round(rate, 2),
                   "sim_days_per_sec": round(rate * dt / 86400.0, 4),
                   "dt60_equivalent": round(rate * 60.0 / 86400.0, 4)}
            rl = _roofline_json(rate, n, **rl_kw)
            if rl is not None:
                row["roofline"] = rl
            out["rows"][name] = row
        except Exception as e:
            log(f"bench precision row {name} unavailable "
                f"({type(e).__name__}: {e})")
            out["rows"][name] = {"skipped": f"{type(e).__name__}: {e}"}
    base = out["rows"].get("f32", {}).get("steps_per_sec")
    hdr = (f"precision report C{n} dt={dt:g}"
           + (" [interpret smoke — NOT a measurement]" if interpret
              else ""))
    log(hdr)
    log(f"  {'row':<14} {'steps/s':>9} {'sd/s/chip':>10} "
        f"{'vs f32':>7} {'AI':>6} {'roof%':>6}")
    for name, row in out["rows"].items():
        if "skipped" in row:
            log(f"  {name:<14} skipped: {row['skipped']}")
            continue
        if base:
            row["speedup_vs_f32"] = round(row["steps_per_sec"] / base, 4)
        rl = row.get("roofline", {})
        pct = rl.get("pct_of_mixed_roof", rl.get("pct_of_compute_roof"))
        log(f"  {name:<14} {row['steps_per_sec']:>9.2f} "
            f"{row['sim_days_per_sec']:>10.4f} "
            f"{row.get('speedup_vs_f32', 1.0):>6.3f}x "
            f"{rl.get('ai', float('nan')):>6.3f} "
            f"{pct if pct is not None else float('nan'):>5}%")
    return out


def bench_perf(n=96, dt=300.0, probe_pallas=True):
    """Performance-observatory section (round 19): hardware identity,
    a full cost stamp of the bench stepper, and a live device-memory
    snapshot — the fields the cross-round regression ledger
    machine-normalizes (``scripts/perf_ledger.py``).

    The stamped stepper mirrors bench's own rung ladder: the covariant
    fused Pallas stepper where it compiles (its flops are INVISIBLE to
    XLA's counter, so the stamp skips the analytic band check and says
    so — the footprint/compile fields are still real), the classic jnp
    stepper otherwise (XLA sees every op; the flops-vs-analytic ratio
    is the cross-check).  The stamp's AOT compile is the recorded
    ``compile_seconds``.  Never raises (returns ``{"skipped": ...}``).
    """
    try:
        import jax
        import jax.numpy as jnp

        from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA,
                                      EARTH_RADIUS)
        from jaxstream.geometry.cubed_sphere import build_grid
        from jaxstream.models.shallow_water_cov import \
            CovariantShallowWater
        from jaxstream.obs import perf as obs_perf
        from jaxstream.physics.initial_conditions import williamson_tc2

        out = {"hardware": jax.devices()[0].platform, "n": n}
        out["memory"] = obs_perf.device_memory_record()
        grid = build_grid(n, halo=2, radius=EARTH_RADIUS,
                          dtype=jnp.float32)
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        rung, step, y = None, None, None
        if probe_pallas:
            try:
                m = CovariantShallowWater(
                    grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA,
                    backend="pallas")
                step = m.make_fused_step(dt)
                y = m.compact_state(m.initial_state(h_ext, v_ext))
                jax.block_until_ready(jax.jit(step)(y,
                                                    jnp.float32(0.0)))
                rung = "cov_fused"
            except Exception as e:
                log(f"bench perf: fused stepper unavailable "
                    f"({type(e).__name__}); stamping the classic rung")
        if rung is None:
            m = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                      omega=EARTH_OMEGA)
            step = m.make_step(dt, "ssprk3")
            y = m.initial_state(h_ext, v_ext)
            rung = "classic"
        stamp = obs_perf.measure_cost(
            step, y, jnp.float32(0.0),
            plan_key=f"bench:{rung}_C{n}",
            analytic=obs_perf.analytic_cost(n),
            xla_visible=(rung == "classic"))
        out["rung"] = rung
        out["cost"] = stamp.to_json()
        log(f"bench perf: {stamp} (hardware {out['hardware']}, "
            f"memory "
            + ("unavailable" if out["memory"].get("unavailable")
               else f"{out['memory']['bytes_in_use']} in use of "
                    f"{out['memory']['limit_bytes']}") + ")")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench perf: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_perf_ledger(rec):
    """Round-19 CI satellite: every bench run (full + ``--smoke``)
    carries the regression ledger's verdict — the assembled record is
    appended to the recorded ``BENCH_r*.json`` trajectory as the
    candidate point and gated against the best comparable history
    (same section, same hardware class; ``jaxstream.obs.perf.
    check_trajectory``).  CPU-smoke candidates are reported-only
    (``enforced: false``); an accelerator run that regressed beyond
    the band stamps ``ok: false`` LOUDLY for the driver.  Never raises
    (reports ``skipped``); asserted by ``tests/test_bench_smoke.py``.
    """
    import os

    try:
        from jaxstream.obs import perf as obs_perf

        root = os.path.dirname(os.path.abspath(__file__))
        points = obs_perf.load_bench_history(root)
        points.append(obs_perf.parse_bench_point(
            {"parsed": rec}, label="candidate"))
        res = obs_perf.check_trajectory(points)
        mode = "ENFORCED" if res["enforced"] else "reported-only"
        log(f"bench perf ledger [{mode}]: {res['points']} points, "
            f"{res['compared_sections']} section(s) compared, "
            f"{len(res['regressions'])} regression(s), "
            f"{len(res['advisories'])} advisory(ies)"
            + ("" if res["ok"] else " — PERF REGRESSION")
            + ("" if res["compared_sections"] or not res["enforced"]
               else " — VACUOUS (no comparable history)"))
        for r in res["regressions"] + res["advisories"]:
            log(f"bench perf ledger: {r['detail']}")
        return res
    except Exception as e:  # never fail the headline metric on this
        log(f"bench perf ledger: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_flight_overhead(n=12, dt=600.0, k=4, windows=12, repeats=9):
    """Round-20 black-box satellite: the always-on flight recorder's
    steady-state cost, measured where it actually runs — per-segment
    ``flight.record`` calls riding a REAL compiled stepping window,
    recorder enabled vs ``flight.disabled()``.  The arms run paired
    back-to-back ``repeats`` times (alternating order) and the
    quietest paired ratio is stamped — see the inline rationale;
    the stamped ``overhead_pct`` is the acceptance
    number behind the "always-on costs < 3%" claim, asserted by
    ``tests/test_bench_smoke.py``.  Smoke windows on CPU, but
    ``record()`` is pure-Python ring bookkeeping, so the ratio
    transfers.  Never raises (returns ``{"skipped": ...}``).
    """
    try:
        import jax
        import jax.numpy as jnp

        from jaxstream.config import (EARTH_GRAVITY, EARTH_OMEGA,
                                      EARTH_RADIUS)
        from jaxstream.geometry.cubed_sphere import build_grid
        from jaxstream.models.shallow_water_cov import \
            CovariantShallowWater
        from jaxstream.obs import flight
        from jaxstream.physics.initial_conditions import williamson_tc2

        grid = build_grid(n, halo=2, radius=EARTH_RADIUS,
                          dtype=jnp.float32)
        h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
        m = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
        step = jax.jit(m.make_step(dt, "ssprk3"))
        y0 = m.initial_state(h_ext, v_ext)
        jax.block_until_ready(step(y0, jnp.float32(0.0)))      # warm

        def window():
            # One serving-shaped window: k compiled steps then the
            # segment-boundary record pair (boundary mark + memory
            # watermark) — the exact steady-state call pattern the
            # server/Simulation loops emit.
            y = y0
            t0 = time.perf_counter()
            for w in range(windows):
                for _ in range(k):
                    y = step(y, jnp.float32(0.0))
                flight.record("segment", step=(w + 1) * k, k=k)
                flight.record("memory.watermark", bytes_in_use=0)
            jax.block_until_ready(y)
            return time.perf_counter() - t0

        # Burn-in: one untimed window per arm, so first-call effects
        # (allocator warmup, cache fill) land on neither timed arm.
        window()
        with flight.disabled():
            window()
        # The recorder's cost is deterministic and tiny (~µs of ring
        # bookkeeping per window) while the stepping wall wanders by
        # whole percents with CPU frequency/scheduler state, so a
        # min-per-arm difference mostly measures that wander.  Pair
        # the arms back-to-back inside each repeat (drift is smallest
        # there), alternate which goes first, and stamp the QUIETEST
        # paired ratio: any repeat where noise hit the arms
        # asymmetrically only moves its ratio away from the true one.
        t_on = t_off = float("inf")
        ratios = []
        for i in range(repeats):
            if i % 2 == 0:
                on = window()
                with flight.disabled():
                    off = window()
            else:
                with flight.disabled():
                    off = window()
                on = window()
            t_on, t_off = min(t_on, on), min(t_off, off)
            ratios.append(on / off)
        overhead = max(0.0, (min(ratios) - 1.0) * 100.0)
        out = {"t_on_s": round(t_on, 5), "t_off_s": round(t_off, 5),
               "overhead_pct": round(overhead, 3),
               "records_per_window": 2 * windows,
               "windows": windows, "k": k, "n": n}
        log(f"bench flight overhead: on {t_on:.4f}s / off "
            f"{t_off:.4f}s -> {overhead:.2f}% "
            f"({windows} windows x {k} steps, best of {repeats})")
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench flight overhead: unavailable "
            f"({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_cold_start(n=8, dt=600.0, buckets="1,2", seg=2, gates=True):
    """Round-21 warm-pool satellite: the compile tax, measured.

    Three arms over one tiny serving config (C{n}, buckets {buckets}):
    a COLD server (no pool — every bucket pays jit), an untimed
    POPULATE pass (pool on, fresh dir — pays the saves), then a WARM
    server restarted against the populated pool.  Stamps server
    cold-start-to-first-result and resize-to-new-bucket wall seconds
    for the cold and warm arms plus their ratios — the numbers the
    perf ledger tracks as ``cold_start:warm_speedup`` /
    ``cold_start:resize_speedup``.

    Gates (acceptance criteria, enforced on every image incl. smoke —
    the margins are ~5x on CPU): both speedups >= 3x, the warm path
    performs ZERO XLA compiles (``compile_count``), and the
    warm-loaded first-segment result byte-equals the fresh-compiled
    one.  Never raises (returns ``{"skipped": ...}``).
    """
    import shutil
    import tempfile

    try:
        import jax

        from jaxstream.serve import EnsembleServer, ScenarioRequest

        blist = sorted({int(b) for b in str(buckets).split(",")})
        b_hi = blist[-1]
        base = {"grid": {"n": n}, "time": {"dt": dt},
                "model": {"name": "shallow_water_cov"},
                "serve": {"buckets": buckets, "segment_steps": seg}}

        def arm(pool_dir):
            cfg = json.loads(json.dumps(base))
            if pool_dir:
                cfg["serve"]["warm_pool"] = pool_dir
            # Each arm starts from an empty jit cache: the cold arm
            # must actually compile even though earlier bench sections
            # warmed similar programs in this process.
            jax.clear_caches()
            t0 = time.perf_counter()
            srv = EnsembleServer(cfg)
            srv.submit(ScenarioRequest(id="r0", ic="tc2", nsteps=seg))
            res = srv.serve()
            first_s = time.perf_counter() - t0
            h = np.asarray(res["r0"].fields["h"])
            t0 = time.perf_counter()
            srv._bucket("any", b_hi)
            resize_s = time.perf_counter() - t0
            out = (first_s, resize_s, h, srv.compile_count(),
                   srv.warmpool_summary())
            srv.close()
            return out

        pdir = tempfile.mkdtemp(prefix="jaxstream_warmpool_")
        try:
            cold_first, cold_resize, h_cold, _, _ = arm(None)
            arm(pdir)                      # populate (untimed)
            (warm_first, warm_resize, h_warm, warm_compiles,
             pool) = arm(pdir)
        finally:
            shutil.rmtree(pdir, ignore_errors=True)

        warm_speedup = cold_first / warm_first if warm_first else 0.0
        resize_speedup = (cold_resize / warm_resize
                          if warm_resize else 0.0)
        byte_equal = h_cold.tobytes() == h_warm.tobytes()
        failures = []
        if gates:
            if warm_speedup < 3.0:
                failures.append(
                    f"cold-start speedup {warm_speedup:.2f}x < 3x")
            if resize_speedup < 3.0:
                failures.append(
                    f"resize speedup {resize_speedup:.2f}x < 3x")
            if warm_compiles != 0:
                failures.append(
                    f"warm path performed {warm_compiles} XLA "
                    "compiles (expected 0)")
            if not byte_equal:
                failures.append(
                    "warm-loaded first segment != fresh-compiled")
        out = {
            "cold_first_result_s": round(cold_first, 3),
            "warm_first_result_s": round(warm_first, 3),
            "warm_speedup": round(warm_speedup, 2),
            "cold_resize_s": round(cold_resize, 3),
            "warm_resize_s": round(warm_resize, 3),
            "resize_speedup": round(resize_speedup, 2),
            "warm_compiles": warm_compiles,
            "byte_equal": bool(byte_equal),
            "hits": pool["hits"] if pool else 0,
            "misses": pool["misses"] if pool else 0,
            "rungs": pool["rungs"] if pool else {},
            "n": n, "buckets": buckets, "segment_steps": seg,
            "ok": not failures,
        }
        if failures:
            out["failures"] = failures
        log(f"bench cold start: first result {cold_first:.2f}s cold / "
            f"{warm_first:.2f}s warm ({warm_speedup:.1f}x), resize "
            f"{cold_resize:.2f}s cold / {warm_resize:.2f}s warm "
            f"({resize_speedup:.1f}x), warm compiles {warm_compiles}, "
            f"byte_equal {byte_equal}"
            + (f" — FAILED: {'; '.join(failures)}" if failures else ""))
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench cold start: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_smoke(n=24, dt=600.0, telemetry=""):
    """``--smoke``: C24, a handful of steps, NO accuracy gates.

    A cheap end-to-end pass through bench's machinery — grid + TC5 ICs,
    rung probing with fallback, the batched ensemble section at
    B in {1, 2}, variant/roofline JSON assembly, exchange-plan
    accounting — wired into a non-slow test (tests/test_bench_smoke.py)
    so bench bitrot is caught by the tier-1 gate instead of the next
    offline TPU run.  Prints exactly ONE JSON line, like main().
    """
    t0 = time.perf_counter()
    try:
        ens = bench_ensemble(n=n, dt=dt, members=(1, 2), warm=1,
                             k1=2, k2=6, gates=False,
                             bitwise_check=False)
    except Exception as e:
        log(f"bench smoke: ensemble section failed "
            f"({type(e).__name__}: {e})")
        ens = {"skipped": f"{type(e).__name__}: {e}"}
    # IO-overlap canary: tiny async-vs-sync-vs-off triple so the async
    # pipeline's bench plumbing is exercised by the tier-1 gate (the
    # rates are smoke windows, NOT measurements — no gate on overhead).
    # nsteps == warm keeps every mode's segment loop on ONE compiled
    # body (the off mode would otherwise jit a second, different-k
    # plain loop for the timed window — pure compile cost, no coverage:
    # both history/checkpoint boundaries and both telemetry records
    # still fire at steps 2 and 4).
    io_sec = bench_io(n=12, dt=dt, nsteps=2, stride=2, warm=2,
                      gates=False)
    # Serving canary (round 11): the continuous-batching server end to
    # end at C16 — packing, per-member masking, boundary refill, the
    # zero-steady-state-recompile bucket claim and the packed-vs-serial
    # comparison all exercised through the REAL bench_serving code path
    # (vmapped classic steppers; rates are smoke windows, NOT
    # measurements).  Asserted by tests/test_bench_smoke.py.
    serving = bench_serving(n=16, dt=dt, bucket=2, n_requests=4, seg=2,
                            backend="jnp", lengths=(4, 7, 2, 5),
                            ic="tc2", gates=False)
    # Multi-chip serving canary (round 12): the member-parallel
    # placement end to end on a 6-fake-device CPU mesh at C12 —
    # sharded masked segments, sharding-preserving refill, the
    # single-vs-multichip h byte-parity and the zero-steady-recompile
    # claim all through the REAL bench_serving_multichip code path.
    # Rates are smoke windows; the 0.8x scaling floor is only enforced
    # on real accelerators (all fake devices share this host's cores).
    serving_mc = bench_serving_multichip(
        n=12, dt=dt, per_chip=1, seg=2, reqs_per_chip=2, mode="member",
        devices=min(6, _device_count()), backend="jnp", ic="tc2",
        lengths=(3, 5), gates=True)
    # Serving-SLO canary (round 14): the network front door end to
    # end on loopback at C8 — real HTTP admission + NDJSON streaming,
    # the closed-loop load harness, live autoscale resizes between the
    # warm {1,2} buckets, and the typed-overload accounting, all
    # through the REAL bench_serving_slo code path.  Latencies are
    # smoke numbers, NOT measurements; the structural floors
    # (accounting exact, >= 1 resize, zero steady recompiles) ARE
    # enforced and asserted by tests/test_bench_smoke.py.
    serving_slo = bench_serving_slo(
        n=8, dt=dt, n_requests=10, seed=714, buckets="1,2", seg=2,
        backend="jnp", queue_capacity=16, lengths=(1, 2, 3, 5),
        mean_gap_s=0.002, tail_alpha=1.4, max_workers=6, gates=True)
    # Assimilation canary (round 18): the EnKF forecast loop end to
    # end at C12 — truth run, seeded station network, batched
    # forecast with the in-loop h_spread stream, the B x B analysis,
    # the free-ensemble baseline — through the REAL
    # bench_assimilation code path.  Rates are smoke windows; the
    # forecast claim (cycled RMSE beats free, zero guard events) IS
    # enforced and asserted by tests/test_bench_smoke.py — this
    # config is calibrated (C12, B=4, 48 stations, sigma 1 m) and
    # measured ~10x RMSE reduction, so the gate is structural, not
    # marginal.
    assimilation = bench_assimilation(
        n=12, dt=dt, members=4, cycles=2, cycle_steps=4,
        nstations=48, obs_sigma=1.0, gates=True)
    # Precision-ladder canary: all four rows (f32 / bf16_stage /
    # mixed16_carry / stacked) through the REAL report code path in
    # interpret mode — structural coverage of the row builders, carry
    # encoders and the precision-corrected roofline JSON; the rates are
    # interpret-mode smoke windows, NOT measurements (the table the
    # driver reads comes from ``--precision-report`` on the TPU host).
    try:
        prec = bench_precision_report(n=12, dt=dt, interpret=True,
                                      warm=1, k1=1, k2=2)
    except Exception as e:
        log(f"bench smoke: precision report failed "
            f"({type(e).__name__}: {e})")
        prec = {"skipped": f"{type(e).__name__}: {e}"}
    # Contract-check stamp (round 13): the static analyzer over the
    # full composition matrix — the tier-1 gate asserts it is both
    # present and CLEAN, so a schedule/stepper invariant breach fails
    # the same gate that runs the parity tests.  smoke=True keeps the
    # stamp trace-only; the compile-level checks run in
    # tests/test_analysis.py within the same gate.
    contract = bench_contract_check(smoke=True)
    # Performance-observatory canary (round 19): the cost stamp +
    # memory snapshot at C12 through the REAL bench_perf code path
    # (classic rung on CPU — XLA sees every op, so the
    # flops-vs-analytic band check runs; memory_stats degrades to the
    # typed unavailable record on CPU), then the regression-ledger
    # stamp over the recorded BENCH_r*.json history with THIS record
    # as the (reported-only, CPU-smoke) candidate — both asserted by
    # tests/test_bench_smoke.py.
    perf = bench_perf(n=12, dt=dt)
    # Flight-recorder overhead stamp (round 20): recorder-on vs
    # recorder-off stepping windows; the envelope carries the number
    # behind the always-on claim (< 3%, asserted by
    # tests/test_bench_smoke.py).
    flight_overhead = bench_flight_overhead(n=12, dt=dt)
    # Warm-pool cold-start canary (round 21): cold vs populated-pool
    # server start and resize-to-new-bucket through the REAL
    # bench_cold_start code path at C8.  The >= 3x speedup, the
    # zero-warm-compiles proof and the byte-equality parity gate ARE
    # enforced (the margins are ~5x even on CPU); asserted by
    # tests/test_bench_smoke.py.  Runs LAST among the jax sections:
    # its arms call jax.clear_caches(), which must not cool any other
    # section's warm executables.
    cold_start = bench_cold_start(n=8, dt=dt, buckets="1,2", seg=2,
                                  gates=True)
    b1 = ens.get("B1", {})
    ok = isinstance(b1, dict) and b1.get("sim_days_per_sec", 0.0) > 0.0
    rec = {
        "metric": f"bench_smoke_TC5_C{n}",
        "smoke": True,
        "value": b1.get("sim_days_per_sec", 0.0)
                 if isinstance(b1, dict) else 0.0,
        "unit": "sim-days/sec (B=1, smoke window — NOT a benchmark)",
        "ok": bool(ok),
        "hardware": perf.get("hardware") or _platform(),
        "ensemble": ens,
        "io": io_sec,
        "serving": serving,
        "serving_multichip": serving_mc,
        "serving_slo": serving_slo,
        "assimilation": assimilation,
        "precision_report": prec,
        "contract_check": contract,
        "perf": perf,
        "flight_overhead": flight_overhead,
        "cold_start": cold_start,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    rec["perf_ledger"] = bench_perf_ledger(rec)
    sink = _open_telemetry(telemetry)
    if sink is not None:
        for key in ("B1", "B2"):
            b = ens.get(key, {})
            if isinstance(b, dict) and "sim_days_per_sec" in b:
                sink.write({"kind": "bench",
                            "metric": f"{rec['metric']}_{key}",
                            "value": b["sim_days_per_sec"],
                            "unit": "sim-days/sec (smoke window)",
                            "steps_per_sec": b.get("steps_per_sec")})
        sink.write({"kind": "bench", "metric": rec["metric"],
                    "value": rec["value"], "unit": rec["unit"],
                    "ok": rec["ok"], "wall_s": rec["wall_s"]})
        sink.close()
    print(json.dumps(rec))
    return 0 if ok else 1


def bench_contract_check(smoke=False):
    """Round-13 CI satellite: every bench run carries a contract-check
    stamp — ``scripts/analyze.py --json`` over the current composition
    matrix (exchange-schedule totality/coverage/depth, traced
    collective counts vs the comm_probe analytic plans, overlap
    windows, precision/donation/callback invariants; see
    jaxstream.analysis).  Runs the CLI's importable ``run()``
    in-process when >= 6 CPU devices exist (the pytest conftest's and
    any flag-started host's pool); otherwise a SUBPROCESS so the
    virtual-host-device flag never touches this process's backends —
    the same policy as bench_multichip.  ``smoke=True`` passes
    ``--no-compile`` (trace-only): the donation-aliasing and
    member-parallel-HLO compiles are covered by tests/test_analysis.py
    in the same tier-1 gate, so the smoke stamp skips their ~35 s while
    the offline full bench keeps every check.  Never raises (reports
    ``skipped``); a non-empty ``violations`` list means the run's
    schedules/steppers broke a proven invariant, and the smoke test
    fails the tier-1 gate on it.
    """
    import os
    import subprocess
    import sys as _sys

    argv = ["--json"] + (["--no-compile"] if smoke else [])
    try:
        import jax

        if len(jax.devices("cpu")) >= 6:
            scripts = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts")
            if scripts not in _sys.path:
                _sys.path.insert(0, scripts)
            import analyze

            code, result, _report = analyze.run(argv)
            result["exit_code"] = code
        else:
            script = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "scripts", "analyze.py")
            r = subprocess.run(
                [_sys.executable, script] + argv,
                capture_output=True, text=True, timeout=1800)
            if r.returncode not in (0, 1) or not r.stdout.strip():
                tail = "\n".join((r.stdout + r.stderr).splitlines()[-5:])
                return {"skipped": f"analyze subprocess failed: {tail}"}
            result = json.loads(r.stdout.strip().splitlines()[-1])
            result["exit_code"] = r.returncode
        # The per-check pass list (~480 entries) is CLI/debug detail;
        # the stamp keeps counts + violations + facts so the bench
        # JSON line and sink records stay readable.
        result.pop("passes", None)
        space = (result.get("facts") or {}).get("plan_space") or {}
        log(f"bench contract check: {result['checks_run']} checks, "
            f"{result['violation_count']} violation(s)"
            + (f"; plan space {space['size']} plans "
               f"(rules v{space['rules_version']})" if space else "")
            + ("" if result["ok"] else " — CONTRACT BROKEN"))
        for v in result.get("violations", [])[:10]:
            log(f"bench contract check: FAIL [{v['check']}] "
                f"{v['subject']}: {v['detail']}")
        return result
    except Exception as e:  # never fail the headline metric on this
        log(f"bench contract check: unavailable "
            f"({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def bench_multichip():
    """Multichip steady-state section: per-stage exchange latency and
    steps/s with the overlapped exchange off vs on, on a 6-device
    ``(panel, 1, 1)`` mesh running the explicit covariant ppermute
    stepper (jaxstream.utils.comm_probe methodology —
    chained-dependency ppermute ping for the per-stage numbers,
    steady-state windows for the rates).  Uses the default platform's
    devices in-process when >= 6 exist (a real slice measures real
    ICI); otherwise runs the structural CPU smoke in a SUBPROCESS
    (scripts/comm_probe.py) so the virtual-host-device flag never
    touches this process's backends — the headline gates and timed run
    keep the exact environment all prior rounds measured in.  Returns
    the dict for the JSON ``multichip`` field; never raises (reports
    ``skipped``).
    """
    import os
    import subprocess
    import sys as _sys

    try:
        import jax

        if len(jax.devices()) >= 6:
            from jaxstream.utils import comm_probe

            cpu = jax.devices()[0].platform == "cpu"
            # temporal_block 2 on the CPU smoke (n=16 fits 3*2*2=12-deep
            # halos), 4 at the real-slice n=96; batched-ensemble rate at
            # a small B either way (one extra stepper compile).
            out = comm_probe.run_default_probe(
                iters=30 if cpu else 100, steps=10 if cpu else 50,
                temporal_block=2 if cpu else 4, members=2 if cpu else 4)
        else:
            script = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "scripts", "comm_probe.py")
            r = subprocess.run(
                [_sys.executable, script, "--iters", "30", "--steps",
                 "10", "--temporal-block", "2", "--members", "2",
                 "--json"],
                capture_output=True, text=True, timeout=1200)
            if r.returncode != 0:
                tail = "\n".join((r.stdout + r.stderr).splitlines()[-5:])
                return {"skipped": f"cpu-smoke subprocess failed: {tail}"}
            out = json.loads(r.stdout.strip().splitlines()[-1])
        from jaxstream.utils.comm_probe import format_report

        for line in format_report(out).splitlines():
            log("bench multichip: " + line)
        return out
    except Exception as e:  # never fail the headline metric on this
        log(f"bench multichip: unavailable ({type(e).__name__}: {e})")
        return {"skipped": f"{type(e).__name__}: {e}"}


def main():
    telemetry = _argv_value("--telemetry")
    if "--compile-report" in sys.argv[1:]:
        raise SystemExit(compile_report())
    if "--precision-report" in sys.argv[1:]:
        # Standalone ladder mode: the four rows measured side by side
        # at the headline grid (ONE JSON line, like main()).  Kept out
        # of the default full run — rows re-measure steppers the
        # variants section already times under gates.
        rep = bench_precision_report()
        print(json.dumps(rep))
        raise SystemExit(
            0 if "skipped" not in rep["rows"].get("f32", {}) else 1)
    if "--smoke" in sys.argv[1:]:
        raise SystemExit(bench_smoke(telemetry=telemetry))
    gates_ok = accuracy_gates()
    value, variants = bench_tc5()
    multichip = bench_multichip()
    contract = bench_contract_check()
    io_section = bench_io(n=96, dt=300.0, nsteps=480, stride=48, warm=48,
                          ic="tc5")
    try:
        ensemble = bench_ensemble()
    except Exception as e:  # never fail the headline metric on this
        log(f"bench ensemble: unavailable ({type(e).__name__}: {e})")
        ensemble = {"skipped": f"{type(e).__name__}: {e}"}
    # Serving section (round 11): packed heterogeneous traffic through
    # the continuous-batching server at the ensemble section's config
    # (C96, dt=300, B=16).  The acceptance floor: the packed rate must
    # recover >= 0.9x the static-B=16 ensemble rate measured above —
    # masking + refill overhead under 10%.
    serving = bench_serving()
    # Multi-chip serving section (round 12): aggregate scaling of one
    # server process driving every device, vs the single-device packed
    # rate at equal per-chip batch.  The >= 0.8x-of-ideal floor is
    # enforced on real accelerators; on a CPU pool the section still
    # proves parity + zero recompiles (floor reported only).
    serving_multichip = bench_serving_multichip()
    # Serving-SLO section (round 14): the network front door under
    # closed-loop heavy-tailed load over loopback HTTP — request
    # latency p50/p99, goodput, typed-shed accounting, live autoscale
    # resizes.  Floors: accounting exact, >= 1 resize, zero steady
    # recompiles, goodput >= 0.5x the packed serving rate measured
    # above, p99 <= 120 s at this calibrated config.
    serving_slo = bench_serving_slo(
        packed_msps=(serving.get("packed", {})
                     .get("member_steps_per_sec")
                     if isinstance(serving, dict) else None),
        p99_floor_s=120.0)
    # Assimilation section (round 18): the EnKF cycle vs the free
    # ensemble on the Galewsky jet — the gated forecast claim.
    assimilation = bench_assimilation()
    # Performance observatory (round 19): the headline stepper's cost
    # stamp (footprint bytes, compile seconds, flops-vs-analytic
    # cross-check on XLA-visible rungs) + live device-memory snapshot.
    perf = bench_perf(n=384, dt=BENCH_DT)
    if isinstance(ensemble, dict) and "packed" in serving:
        msps = (ensemble.get("B16") or {}).get("member_steps_per_sec")
        if msps:
            ratio = serving["packed"]["member_steps_per_sec"] / msps
            serving["vs_static_B16"] = round(ratio, 4)
            serving["meets_0p9_floor"] = bool(ratio >= 0.9)
            log(f"bench serving: packed/static-B16 = {ratio:.3f}x "
                f"(floor 0.9: "
                f"{'OK' if ratio >= 0.9 else 'BREACHED'})")
    try:
        vg, rg = bench_galewsky()
        # nu4='split': the re-derived 210 flops/cell/step filter count
        # plus the split placement's ~6 extra f32 field passes (the old
        # scale=4/3 billed the filter as one extra 137-flop stage, ~35%
        # under — round-10 accounting satellite).  Gate breach keeps
        # the entry shape (every variant is a dict).
        variants["galewsky_nu4_C384"] = (
            _variant_entry(vg, rg, 384, nu4="split", dt=60.0)
            if rg > 0 else {"sim_days_per_sec": 0.0})
    except Exception as e:
        log(f"bench variant galewsky unavailable ({type(e).__name__}: {e})")
    try:
        # Re-fused del^4 line (round 10): the filter commuted into the
        # stage-1 kernel — 3 kernels + 3 routes per step vs split's
        # 4 + 4 — behind the IDENTICAL day-6 physics gate (vorticity
        # bands, quiescent hemisphere, mass) so the equivalence claim
        # is re-proven on every bench run.
        vgr, rgr = bench_galewsky(nu4_mode="refused")
        variants["galewsky_nu4_refused_C384"] = (
            _variant_entry(vgr, rgr, 384, nu4="refused", dt=60.0)
            if rgr > 0 else {"sim_days_per_sec": 0.0})
    except Exception as e:
        log(f"bench variant galewsky-refused unavailable "
            f"({type(e).__name__}: {e})")
    if not gates_ok:
        # Variants were measured on the same breached discretization —
        # publish none of them (gate log lines on stderr remain).
        log("bench: ACCURACY/STABILITY GATE BREACH — reporting value 0 "
            "and suppressing all variant lines")
        value = 0.0
        variants = {}
        ensemble = {"suppressed": "accuracy/stability gate breach"}
        serving = {"suppressed": "accuracy/stability gate breach"}
        serving_multichip = {"suppressed":
                             "accuracy/stability gate breach"}
        serving_slo = {"suppressed": "accuracy/stability gate breach"}
        assimilation = {"suppressed": "accuracy/stability gate breach"}
    # dt is part of the metric's definition (sim-days/sec = steps/s * dt);
    # emit it top-level, with the dt=60-equivalent rate adjacent, so
    # cross-round comparisons of `value` are self-describing.
    dt60 = variants.pop("dt60_equivalent", round(value * 60.0 / BENCH_DT, 4))
    # Warm-pool cold start (round 21): cold vs populated-pool server
    # start-to-first-result and resize-to-new-bucket, with the >= 3x
    # speedup / zero-warm-compiles / byte-equality gates enforced.
    # Runs LAST among the jax sections: its arms clear the jit caches,
    # which must not cool any timed executable above.
    cold_start = bench_cold_start(n=8, dt=600.0, buckets="1,2", seg=2,
                                  gates=True)
    sink = _open_telemetry(telemetry)
    if sink is not None:
        sink.write({"kind": "bench",
                    "metric": "sim_days_per_sec_per_chip_TC5_C384",
                    "value": round(value, 4),
                    "unit": "sim-days/sec/chip", "dt": BENCH_DT,
                    "gates_ok": bool(gates_ok)})
        for name, v in variants.items():
            if isinstance(v, dict) and "sim_days_per_sec" in v:
                sink.write({"kind": "bench", "metric": f"variant_{name}",
                            "value": v["sim_days_per_sec"],
                            "unit": "sim-days/sec/chip",
                            "steps_per_sec": v.get("steps_per_sec")})
        if isinstance(serving, dict) and "packed" in serving:
            p = serving["packed"]
            sink.write({
                "kind": "bench", "metric": "serving_packed",
                "value": p["agg_sim_days_per_sec_per_chip"],
                "unit": "aggregate sim-days/sec/chip",
                "member_steps_per_sec": p["member_steps_per_sec"],
                "occupancy_mean": p["occupancy_mean"],
                "latency_p50_s": p["latency_p50_s"],
                "latency_p99_s": p["latency_p99_s"],
                "vs_static_B16": serving.get("vs_static_B16")})
        if (isinstance(serving_multichip, dict)
                and "multichip" in serving_multichip):
            m = serving_multichip["multichip"]
            sink.write({
                "kind": "bench", "metric": "serving_multichip",
                "value": m["agg_sim_days_per_sec"],
                "unit": "aggregate sim-days/sec (whole mesh)",
                "devices": serving_multichip["devices"],
                "mode": serving_multichip["mode"],
                "scaling_vs_ideal":
                    serving_multichip.get("scaling_vs_ideal"),
                "meets_0p8_floor":
                    serving_multichip.get("meets_0p8_floor")})
        if (isinstance(assimilation, dict)
                and "cycled_final_rmse" in assimilation):
            sink.write({
                "kind": "bench", "metric": "assimilation",
                "value": assimilation["rmse_reduction"],
                "unit": "m RMSE reduction vs free ensemble",
                "cycled_final_rmse":
                    assimilation["cycled_final_rmse"],
                "free_final_rmse": assimilation["free_final_rmse"],
                "beats_free_run": assimilation["beats_free_run"],
                "members": assimilation["members"],
                "cycles": assimilation["cycles"]})
        if isinstance(serving_slo, dict) and "slo" in serving_slo:
            slo = serving_slo["slo"]
            sink.write({
                "kind": "bench", "metric": "serving_slo",
                "value": slo["goodput_member_steps_per_sec"],
                "unit": "member-steps/sec goodput (HTTP loopback)",
                "latency_p50_s": slo["latency_p50_s"],
                "latency_p99_s": slo["latency_p99_s"],
                "completed": slo["completed"], "shed": slo["shed"],
                "resizes": serving_slo.get("resizes"),
                "goodput_vs_packed":
                    serving_slo.get("goodput_vs_packed"),
                "meets_goodput_floor":
                    serving_slo.get("meets_goodput_floor"),
                "meets_p99_floor":
                    serving_slo.get("meets_p99_floor")})
        if isinstance(cold_start, dict) and "warm_speedup" in cold_start:
            sink.write({"kind": "bench", "metric": "cold_start",
                        "value": cold_start["warm_speedup"],
                        "unit": "warm-over-cold start speedup (x)",
                        "resize_speedup": cold_start["resize_speedup"],
                        "warm_compiles": cold_start["warm_compiles"],
                        "byte_equal": cold_start["byte_equal"]})
        sink.close()
    record = {
        "metric": "sim_days_per_sec_per_chip_TC5_C384",
        "value": round(value, 4),
        "unit": "sim-days/sec/chip",
        "vs_baseline": round(value / BASELINE_PER_CHIP, 4),
        "dt": BENCH_DT,
        "dt60_equivalent": dt60,
        "hardware": perf.get("hardware") or _platform(),
        "roofline": (_roofline_json(value * 86400.0 / BENCH_DT, 384)
                     if value > 0 else None),
        "variants": variants,
        "ensemble": ensemble,
        "serving": serving,
        "serving_multichip": serving_multichip,
        "serving_slo": serving_slo,
        "assimilation": assimilation,
        "io": io_section,
        "multichip": multichip,
        "contract_check": contract,
        "perf": perf,
        "cold_start": cold_start,
    }
    # The regression-ledger stamp gates THIS record against the
    # recorded BENCH_r*.json trajectory (enforced on accelerator
    # hardware; the smoke path stamps reported-only).
    record["perf_ledger"] = bench_perf_ledger(record)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
