"""Headline benchmark: simulated-days/sec/chip, Williamson TC5 at C384.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline (BASELINE.json north star): >=1000 simulated-days/sec on a
v5p-256 pod => 1000/256 = 3.90625 sim-days/sec/chip. ``vs_baseline`` is
our per-chip rate divided by that. A TC2 L2-height-error parity check at
C48 runs first (stderr only) and marks the result invalid if it fails.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_PER_CHIP = 1000.0 / 256.0  # sim-days/sec/chip


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def tc2_parity(n=48, hours=24.0):
    """Short TC2 run; returns normalized L2 height error (steady state).

    Uses the covariant formulation — the throughput section's first-choice
    stepper — so the gate and the benchmark test the same discretization
    (fallback rungs use the Cartesian formulation, whose TC2 error is the
    same to within 3%; tests/test_cov_swe.py).
    """
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc2

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    model = CovariantShallowWater(grid, gravity=EARTH_GRAVITY,
                                  omega=EARTH_OMEGA)
    h_ext, v_ext = williamson_tc2(grid, EARTH_GRAVITY, EARTH_OMEGA)
    state = model.initial_state(h_ext, v_ext)
    dt = 300.0
    nsteps = int(hours * 3600 / dt)
    out, _ = model.run(state, nsteps, dt)
    h0 = np.asarray(state["h"], dtype=np.float64)
    h1 = np.asarray(out["h"], dtype=np.float64)
    area = np.asarray(grid.interior(grid.area), dtype=np.float64)
    err = np.sqrt(np.sum(area * (h1 - h0) ** 2) / np.sum(area * h0**2))
    return float(err)


def bench_tc5(n=384, dt=60.0, warm_steps=10, timed_steps=6000):
    import jax
    import jax.numpy as jnp

    from jaxstream.config import EARTH_GRAVITY, EARTH_OMEGA, EARTH_RADIUS
    from jaxstream.geometry.cubed_sphere import build_grid
    from jaxstream.models.shallow_water import ShallowWater
    from jaxstream.models.shallow_water_cov import CovariantShallowWater
    from jaxstream.physics.initial_conditions import williamson_tc5
    from jaxstream.stepping import integrate

    grid = build_grid(n, halo=2, radius=EARTH_RADIUS, dtype=jnp.float32)
    h_ext, v_ext, b_ext = williamson_tc5(grid, EARTH_GRAVITY, EARTH_OMEGA)

    # Fastest-first ladder, probing one real step of each candidate so a
    # Mosaic compile failure (VMEM/shape limits, CPU bench runs) falls
    # through instead of crashing:
    #   1. covariant fused stepper (3 fields, rotation strips; ~1.4x the
    #      Cartesian fused stepper at C384),
    #   2. Cartesian fused stepper (in-kernel exchange),
    #   3. classic jnp SSPRK3.
    state = step = None
    try:
        model = CovariantShallowWater(
            grid, gravity=EARTH_GRAVITY, omega=EARTH_OMEGA, b_ext=b_ext,
            backend="pallas")
        step = model.make_fused_step(dt)
        y = model.compact_state(model.initial_state(h_ext, v_ext))
        jax.block_until_ready(jax.jit(step)(y, jnp.float32(0.0)))
        state = y
        log("bench: using covariant compact fused SSPRK3 stepper "
            "(interior-only carry, rotation strips)")
    except Exception as e:
        log(f"bench: covariant fused stepper unavailable "
            f"({type(e).__name__}: {e})")
    if state is None:
        try:
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext,
                                 backend="pallas")
            step = model.make_fused_step(dt, in_kernel_exchange=True)
            y = model.extend_state(model.initial_state(h_ext, v_ext),
                                   with_strips=True)
            jax.block_until_ready(jax.jit(step)(y, jnp.float32(0.0)))
            state = y
            log("bench: using Cartesian fused SSPRK3 stepper "
                "(in-kernel exchange)")
        except Exception as e:
            log(f"bench: Cartesian fused stepper unavailable "
                f"({type(e).__name__}: {e})")
    if state is None:
        # Classic stepper; plain Pallas RHS kernel if it compiles (the
        # fused stage kernels have stricter VMEM/shape needs), jnp last.
        try:
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext,
                                 backend="pallas")
            state = model.initial_state(h_ext, v_ext)
            jax.block_until_ready(model.rhs(state, 0.0)["h"])
            log("bench: using classic stepper with pallas RHS kernel")
        except Exception as e:
            log(f"bench: pallas RHS unavailable ({type(e).__name__}); "
                f"using jnp")
            model = ShallowWater(grid, gravity=EARTH_GRAVITY,
                                 omega=EARTH_OMEGA, b_ext=b_ext)
            state = model.initial_state(h_ext, v_ext)
        step = model.make_step(dt, "ssprk3")

    # One compiled executable for any step count: nsteps rides the carry as
    # a traced bound (fori_loop lowers to a while), so the timed region is
    # pure device execution — no recompile between warmup and timing (the
    # reference's "no recompilation during timestepping" invariant, deck
    # p.10, applied to the benchmark harness itself).
    run = jax.jit(
        lambda y, nsteps: integrate(step, y, 0.0, nsteps, dt), donate_argnums=0
    )

    t0 = time.perf_counter()
    state_w, _ = run(state, warm_steps)
    jax.block_until_ready(state_w)
    log(f"bench: warmup {warm_steps} steps (incl. compile) "
        f"{time.perf_counter() - t0:.1f}s on {jax.devices()[0].platform}")

    t0 = time.perf_counter()
    out, _ = run(state_w, timed_steps)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0

    h = np.asarray(out["h"])
    if not np.all(np.isfinite(h)):
        raise RuntimeError("bench run produced non-finite h")
    steps_per_sec = timed_steps / wall
    sim_days_per_sec = steps_per_sec * dt / 86400.0
    log(f"bench: C{n} TC5 {timed_steps} steps in {wall:.2f}s "
        f"({steps_per_sec:.1f} steps/s, dt={dt}s)")
    try:  # roofline context (deck p.19's analysis frame; best-effort)
        from jaxstream.utils.profiling import TPU_V5E, roofline

        r = roofline(jax.jit(step), out, jnp.float32(0.0),
                     seconds=1.0 / steps_per_sec, roof=TPU_V5E)
        log("bench: " + r.report())
    except Exception as e:
        log(f"bench: roofline unavailable ({e})")
    return sim_days_per_sec


def main():
    err = tc2_parity()
    log(f"bench: TC2 C48 24h normalized L2 height error = {err:.3e}")
    # Truncation-error budget: C48 day-1 normalized L2(h) is 1.10e-3 at
    # float64 AND float32 (measured) — the scheme's truncation, not
    # precision loss; parity means f32-on-TPU stays at that level.
    parity_ok = err < 2e-3

    value = bench_tc5()
    if not parity_ok:
        log("bench: TC2 PARITY FAILED — reporting value 0")
        value = 0.0
    print(json.dumps({
        "metric": "sim_days_per_sec_per_chip_TC5_C384",
        "value": round(value, 4),
        "unit": "sim-days/sec/chip",
        "vs_baseline": round(value / BASELINE_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
